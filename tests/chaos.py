"""Deterministic fault-injection harness for the sync/partials hot path.

Everything is driven by (a) a seed and (b) an auto-advancing fake clock, so
a chaos run is byte-for-byte reproducible: fault decisions are STATELESS
functions of (seed, peer, stream#, item#) — they do not consume a shared
RNG stream, so thread interleaving in the sync pump cannot perturb them —
and every retry/backoff/cooldown wait jumps the clock instead of sleeping.

Building blocks:
  * `AutoClock`    — FakeClock whose waiters advance time themselves.
  * `FaultPlan`    — per-peer probabilities for drop / delay /
                     corrupt-signature / truncate-stream, plus a
                     crash-restart window in fake time.
  * `ChaosStream`  — wraps any beacon iterator with the plan's faults.
  * `ChaosStore`   — wraps any chain Store, corrupting / dropping reads.
  * `build_chain`  — real-crypto 1-of-1 chain (the MockChain pattern).
  * `ChaosScenario`— N-node sync network, some peers Byzantine; honest
                     nodes sync through breaker-aware SyncManagers and must
                     converge to one identical verified chain.
  * `StorageFaultPlan` / `inject_storage_faults` — seeded AT-REST faults
                     (torn write, bit flip, deleted row) written INTO a
                     store, for the chain-integrity scan/repair path
                     (chain/integrity.py, tools/chain_doctor.py).
  * `DeviceFaultPlan` / `FaultyDeviceBackend` — seeded DEVICE faults
                     injected at the verify-service backend boundary
                     (hang-forever, raise-on-dispatch, flappy window,
                     poisoned/wrong-shape result), zero real-chip
                     dependency.
  * `DeviceChaosScenario` — mixed live/background workload through a
                     flapping device: every future must resolve with
                     verdicts identical to a host-only run, failover
                     within one watchdog deadline, re-promotion after
                     recovery.
  * `DeviceFailoverSyncScenario` — kill the device backend mid-catch-up
                     sync on a 3-node network; convergence must come via
                     the host path before the round deadline.
  * `OverloadScenario` — seeded serving-plane overload (public read
                     flood + one sync-hog peer during live rounds)
                     against the admission controller: partials
                     admission p99 stays bounded, every shed is
                     well-formed, the verify background lane pauses
                     before any normal-class shed, the hog's drain rate
                     is fair-share bounded, and the ladder recovers.
"""

import hashlib
import os
import random
import threading
import types

import numpy as np
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from drand_tpu.beacon.clock import FakeClock
from drand_tpu.beacon.sync import SyncManager
from drand_tpu.chain.beacon import Beacon
from drand_tpu.chain.errors import ErrNoBeaconSaved
from drand_tpu.chain.memdb import MemDBStore
from drand_tpu.core.follow import FollowFacade
from drand_tpu.crypto.hostverify import HostBatchVerifier
from drand_tpu.crypto.schemes import scheme_from_name
from drand_tpu.net.resilience import (BackoffPolicy, BreakerRegistry,
                                      ResiliencePolicy)


def stable_seed(*parts) -> int:
    """Process-independent 32-bit seed (builtin hash() of a str is salted
    per process — useless for cross-run reproducibility)."""
    blob = "/".join(str(p) for p in parts).encode()
    return int.from_bytes(hashlib.sha256(blob).digest()[:4], "big")


class AutoClock(FakeClock):
    """FakeClock whose waiters advance time themselves: `wait_until` jumps
    straight to the deadline.  Backoff schedules, breaker cooldowns, and
    deadline budgets all elapse instantly AND deterministically — fake time
    only moves when someone asks to wait for it."""

    def wait_until(self, deadline: float, stop: threading.Event) -> bool:
        if stop.is_set():
            return False
        with self._cond:
            if deadline > self._now:
                self._now = deadline
                self._cond.notify_all()
        return True

    def jump(self, dt: float) -> None:
        """advance() that tolerates concurrent callers (fault injectors
        advance from stream pump threads)."""
        with self._cond:
            self._now += dt
            self._cond.notify_all()


@dataclass
class FaultPlan:
    """Per-peer fault schedule.  Probabilities are evaluated by a stateless
    seeded hash per (stream, item), so two runs with the same seed inject
    the same fault at the same point no matter how threads interleave."""

    seed: int = 0
    drop: float = 0.0            # P(raise ConnectionError) per item
    delay: float = 0.0           # P(advance the fake clock) per item
    delay_s: float = 7.0         # how far one injected delay jumps
    corrupt: float = 0.0         # P(flip signature bytes) per item
    truncate: float = 0.0        # P(end the stream early) per item
    crash_at: Optional[float] = None      # fake-time window in which the
    restart_at: Optional[float] = None    # peer refuses all connections

    def dice(self, stream: int, item: int) -> random.Random:
        return random.Random(stable_seed(self.seed, stream, item))

    def down(self, now: float) -> bool:
        return (self.crash_at is not None and now >= self.crash_at
                and (self.restart_at is None or now < self.restart_at))


def corrupt_signature(b: Beacon) -> Beacon:
    """Flip bits in the signature: still parses as 96/48 bytes but fails
    verification (a Byzantine peer serving forged beacons)."""
    sig = bytearray(b.signature)
    sig[len(sig) // 2] ^= 0xFF
    return Beacon(round=b.round, signature=bytes(sig),
                  previous_sig=b.previous_sig)


class ChaosStream:
    """Wrap a beacon iterator with a FaultPlan.  `events` collects
    (peer, stream#, item#, fault) tuples for post-run inspection."""

    def __init__(self, source, plan: FaultPlan, clock, peer: str,
                 stream_no: int, events: Optional[List[tuple]] = None):
        self.source = iter(source)
        self.plan = plan
        self.clock = clock
        self.peer = peer
        self.stream_no = stream_no
        self.events = events if events is not None else []
        self._i = 0

    def _log(self, fault: str) -> None:
        self.events.append((self.peer, self.stream_no, self._i, fault))

    def __iter__(self):
        return self

    def __next__(self) -> Beacon:
        if self.plan.down(self.clock.now()):
            self._log("crash")
            raise ConnectionError(f"{self.peer} is down (crash window)")
        item = next(self.source)
        dice = self.plan.dice(self.stream_no, self._i)
        self._i += 1
        if dice.random() < self.plan.drop:
            self._log("drop")
            raise ConnectionError(f"{self.peer} dropped the connection")
        if dice.random() < self.plan.delay:
            self._log("delay")
            # a slow peer burns the caller's deadline budget
            jump = getattr(self.clock, "jump", None)
            if jump is not None:
                jump(self.plan.delay_s)
        if dice.random() < self.plan.truncate:
            self._log("truncate")
            raise StopIteration
        if dice.random() < self.plan.corrupt:
            self._log("corrupt")
            return corrupt_signature(item)
        return item


class ChaosStore:
    """Store decorator injecting read faults: `drop` raises
    ErrNoBeaconSaved (lost row), `corrupt` returns a forged beacon.  A
    round re-written THROUGH this wrapper (the repair path's delete+put)
    is considered healed — the bad sector got replaced — and reads
    faithfully from then on, so `check → repair → re-check` really
    exercises the RAW-store write path."""

    def __init__(self, raw, plan: FaultPlan):
        self.raw = raw
        self.plan = plan
        self._healed = set()

    def _fault(self, b: Beacon):
        """The per-round fault verdict, shared by get() and the cursor
        (the integrity scanner reads through cursors — a bad sector must
        fault on EVERY read path, not just point lookups).  Returns None
        for a lost row, a forged beacon for a corrupt one."""
        if b is None or b.round in self._healed:
            return b
        dice = self.plan.dice(0, b.round)
        if dice.random() < self.plan.drop:
            return None
        if dice.random() < self.plan.corrupt:
            return corrupt_signature(b)
        return b

    def get(self, round_: int) -> Beacon:
        b = self._fault(self.raw.get(round_))
        if b is None:
            raise ErrNoBeaconSaved(f"round {round_} lost")
        return b

    def cursor(self):
        return _ChaosCursor(self)

    def put(self, b: Beacon) -> None:
        self._healed.add(b.round)
        self.raw.put(b)

    def put_many(self, beacons) -> None:
        # must route through OUR put so repaired rounds count as healed
        for b in beacons:
            self.put(b)

    def delete(self, round_: int) -> None:
        self._healed.add(round_)
        self.raw.delete(round_)

    def __getattr__(self, name):
        return getattr(self.raw, name)


class _ChaosCursor:
    """Cursor over a ChaosStore: lost rows are skipped (a hole, exactly
    what a cursor over a store missing that row would produce), corrupt
    rows come back forged."""

    def __init__(self, store: ChaosStore):
        self._store = store
        self._cur = store.raw.cursor()

    def _skip_dropped(self, b, advance):
        while b is not None:
            faulted = self._store._fault(b)
            if faulted is not None:
                return faulted
            b = advance()
        return None

    def first(self):
        return self._skip_dropped(self._cur.first(), self._cur.next)

    def next(self):
        return self._skip_dropped(self._cur.next(), self._cur.next)

    def seek(self, round_: int):
        return self._skip_dropped(self._cur.seek(round_), self._cur.next)

    def last(self):
        # no backwards walk in the Cursor API: a dropped head reads as a
        # forged None-free pass-through (head detection stays raw)
        return self._store._fault(self._cur.last()) or self._cur.last()

    def __iter__(self):
        b = self.first()
        while b is not None:
            yield b
            b = self.next()


# ---------------------------------------------------------------------------
# storage faults at rest (the chain-doctor target): unlike ChaosStore's
# read-path faults, these mutate the stored rows themselves — what a crash
# mid-write, a bad sector, or an operator's stray DELETE leaves behind.
# ---------------------------------------------------------------------------

TORN_WRITE = "torn_write"      # row exists but the blob is truncated
BIT_FLIP = "bit_flip"          # right length, one bit of the signature off
DELETED_ROW = "deleted_row"    # row gone entirely


@dataclass
class StorageFaultPlan:
    """Seeded at-rest fault assignment.  `assign` is a pure function of
    (seed, max_round), so a scenario replay corrupts the same rounds the
    same way regardless of interleaving."""

    seed: int = 0
    torn_writes: int = 1
    bit_flips: int = 1
    deleted_rows: int = 1

    def assign(self, max_round: int) -> Dict[int, str]:
        total = self.torn_writes + self.bit_flips + self.deleted_rows
        if total > max_round:
            raise ValueError(f"{total} faults > {max_round} rounds")
        rng = random.Random(stable_seed(self.seed, "storage-faults"))
        rounds = rng.sample(range(1, max_round + 1), total)
        kinds = ([TORN_WRITE] * self.torn_writes
                 + [BIT_FLIP] * self.bit_flips
                 + [DELETED_ROW] * self.deleted_rows)
        return dict(zip(rounds, kinds))


def inject_storage_faults(store, plan: StorageFaultPlan,
                          max_round: int) -> Dict[int, str]:
    """Write the plan's faults into `store` (any chain.Store; the
    delete-then-put dance is needed because memdb ignores duplicate-round
    puts).  Returns {round: fault_kind} for post-run assertions."""
    faults = plan.assign(max_round)
    for r, kind in sorted(faults.items()):
        if kind == DELETED_ROW:
            store.delete(r)
            continue
        b = store.get(r)
        if kind == BIT_FLIP:
            sig = bytearray(b.signature)
            sig[len(sig) // 3] ^= 0x01
            sig = bytes(sig)
        else:                                   # TORN_WRITE
            sig = b.signature[:len(b.signature) // 2]
        store.delete(r)
        store.put(Beacon(round=r, signature=sig,
                         previous_sig=b.previous_sig))
    return faults


# ---------------------------------------------------------------------------
# chain + scenario
# ---------------------------------------------------------------------------


class TrueChain:
    """Real-crypto 1-of-1 chain (the MockChain pattern from test_client,
    duplicated here so tools/chaos_smoke.py can import the harness without
    dragging the test modules in)."""

    def __init__(self, scheme_id="pedersen-bls-chained", n=24,
                 seed: bytes = b"chaos-chain"):
        self.scheme = scheme_from_name(scheme_id)
        sec, pub = self.scheme.keypair(seed=seed)
        self.public = self.scheme.public_bytes(pub)
        self.genesis_seed = b"\x07" * 32
        self.n = n
        self.beacons: Dict[int, Beacon] = {}
        prev = self.genesis_seed if self.scheme.chained else None
        for r in range(1, n + 1):
            msg = self.scheme.digest_beacon(
                r, prev if self.scheme.chained else None)
            sig = self.scheme.sign(sec, msg)
            self.beacons[r] = Beacon(
                round=r, signature=sig,
                previous_sig=prev if self.scheme.chained else None)
            prev = sig


@dataclass
class ScenarioResult:
    converged: bool
    chain_digest: str                       # sha256 over all stored sigs
    events: List[tuple] = field(default_factory=list)
    breaker_snapshots: Dict[str, Dict[str, str]] = field(default_factory=dict)


class ChaosScenario:
    """N-node sync network with Byzantine members.

    Node 0 is the honest seed holding the full true chain; the remaining
    honest nodes start empty and sync from ALL other nodes (Byzantine ones
    included) through breaker-aware SyncManagers.  Byzantine peers serve
    the true chain mangled by their FaultPlan.  Honest nodes that have
    already synced serve from their own stores, so later nodes genuinely
    depend on earlier convergence."""

    def __init__(self, seed: int, n_nodes: int = 5, n_byzantine: int = 2,
                 rounds: int = 24, period: int = 30,
                 byzantine_plan: Optional[dict] = None,
                 breaker_failures: int = 2, breaker_cooldown: float = 5.0,
                 sync_budget: float = 10_000.0,
                 chain: Optional[TrueChain] = None):
        assert n_byzantine < n_nodes - 1, "need at least 2 honest nodes"
        self.seed = seed
        self.clock = AutoClock(start=1_000.0)
        # the real-crypto chain is the expensive part; determinism tests
        # reuse one instance across scenario replays (it is read-only here)
        self.chain = chain if chain is not None and chain.n >= rounds \
            else TrueChain(n=rounds)
        self.rounds = rounds
        self.period = period
        self.events: List[tuple] = []
        self.addresses = [f"node{i}" for i in range(n_nodes)]
        # Byzantine assignment is part of the seed-derived determinism:
        # the LAST n_byzantine addresses, faults seeded per peer
        self.byzantine = set(self.addresses[-n_byzantine:])
        plan_kw = dict(drop=0.25, delay=0.2, corrupt=0.35, truncate=0.15)
        plan_kw.update(byzantine_plan or {})
        self.plans = {a: FaultPlan(seed=stable_seed(seed, a), **plan_kw)
                      for a in self.byzantine}
        self._stream_no: Dict[str, int] = {}
        self.breaker_failures = breaker_failures
        self.breaker_cooldown = breaker_cooldown
        self.sync_budget = sync_budget
        # honest nodes: node 0 pre-seeded, the rest empty
        self.stores: Dict[str, MemDBStore] = {}
        self.facades: Dict[str, FollowFacade] = {}
        for a in self.addresses:
            if a in self.byzantine:
                continue
            store = MemDBStore(buffer_size=rounds + 8)
            facade = FollowFacade(store, self.chain.scheme.chained,
                                  self.chain.genesis_seed)
            if a == self.addresses[0]:
                for r in range(1, rounds + 1):
                    facade.put(self.chain.beacons[r])
            self.stores[a] = store
            self.facades[a] = facade

    # -- serving side --------------------------------------------------------

    def _serve(self, peer: str, from_round: int):
        """What `peer` would stream for a SyncChain request."""
        if peer in self.byzantine:
            # Byzantine peers claim the whole chain, then mangle it
            for r in range(from_round, self.rounds + 1):
                yield self.chain.beacons[r]
            return
        facade = self.facades.get(peer)
        if facade is None:
            return
        store = self.stores[peer]
        for r in range(from_round, self.rounds + 1):
            try:
                yield store.get(r)
            except Exception:
                return      # an honest node serves only what it has

    def fetch(self, peer, from_round: int):
        peer = str(peer)
        src = self._serve(peer, from_round)
        plan = self.plans.get(peer)
        if plan is None:
            return src
        no = self._stream_no.get(peer, 0)
        self._stream_no[peer] = no + 1
        return ChaosStream(src, plan, self.clock, peer, no, self.events)

    # -- the run -------------------------------------------------------------

    def _manager(self, addr: str) -> SyncManager:
        policy = ResiliencePolicy(
            clock=self.clock,
            backoff=BackoffPolicy(base=0.2, cap=2.0),
            breakers=BreakerRegistry(clock=self.clock,
                                     failures=self.breaker_failures,
                                     cooldown=self.breaker_cooldown,
                                     scope=f"chaos-{addr}"),
            scope=f"chaos-{addr}",
            seed=stable_seed(self.seed, addr))
        peers = [a for a in self.addresses if a != addr]
        return SyncManager(
            chain=self.facades[addr], scheme=self.chain.scheme,
            public_key_bytes=self.chain.public, period=self.period,
            clock=self.clock, fetch=self.fetch, peers=peers, chunk=8,
            verifier=HostBatchVerifier(self.chain.scheme, self.chain.public),
            resilience=policy, sync_budget=self.sync_budget)

    def run(self) -> ScenarioResult:
        """Sync every empty honest node to the target round; returns the
        convergence verdict plus the per-node breaker snapshots."""
        snapshots: Dict[str, Dict[str, str]] = {}
        digests = []
        converged = True
        for addr in self.addresses:
            if addr in self.byzantine or addr == self.addresses[0]:
                continue
            syncm = self._manager(addr)
            try:
                syncm.sync(self.rounds, syncm.peers)
            except Exception:
                converged = False
            snapshots[addr] = syncm.resilience.breakers.snapshot()
            # converged = full chain present AND it re-verifies
            faulty = syncm.check_past_beacons(self.rounds)
            if faulty:
                converged = False
        for addr in sorted(self.facades):
            h = hashlib.sha256()
            store = self.stores[addr]
            for r in range(1, self.rounds + 1):
                try:
                    h.update(store.get(r).signature)
                except Exception:
                    h.update(b"missing")
                    converged = False
            digests.append(h.hexdigest())
        if len(set(digests)) != 1:
            converged = False
        return ScenarioResult(converged=converged,
                              chain_digest=digests[0],
                              events=list(self.events),
                              breaker_snapshots=snapshots)


# ---------------------------------------------------------------------------
# storage chaos: corrupt one node's store at rest, prove the integrity
# scan detects it, the heal path repairs from peers, and the post-repair
# full-crypto rescan comes back clean — zero real I/O (fake clock,
# in-memory peers)
# ---------------------------------------------------------------------------


@dataclass
class StorageScenarioResult:
    injected: Dict[int, str]            # round -> fault kind
    detected_rounds: List[int]          # faulty rounds the scan flagged
    all_detected: bool                  # every injected round was flagged
    unrepaired: List[int]
    rescan_clean: bool
    converged: bool                     # all nodes byte-identical again
    chain_digest: str

    @property
    def ok(self) -> bool:
        return (self.all_detected and not self.unrepaired
                and self.rescan_clean and self.converged)


class StorageChaosScenario:
    """N honest nodes all holding the full true chain; node 0's store gets
    seeded at-rest faults.  run() = scan → heal(from peers) → rescan."""

    def __init__(self, seed: int, n_nodes: int = 3, rounds: int = 24,
                 torn_writes: int = 1, bit_flips: int = 1,
                 deleted_rows: int = 1, chain: Optional[TrueChain] = None):
        assert n_nodes >= 2, "need at least one healthy peer"
        self.seed = seed
        self.rounds = rounds
        self.clock = AutoClock(start=1_000.0)
        self.chain = chain if chain is not None and chain.n >= rounds \
            else TrueChain(n=rounds)
        self.addresses = [f"node{i}" for i in range(n_nodes)]
        self.victim = self.addresses[0]
        self.plan = StorageFaultPlan(seed=stable_seed(seed, "at-rest"),
                                     torn_writes=torn_writes,
                                     bit_flips=bit_flips,
                                     deleted_rows=deleted_rows)
        self.stores: Dict[str, MemDBStore] = {}
        for a in self.addresses:
            store = MemDBStore(buffer_size=rounds + 8)
            for r in range(1, rounds + 1):
                store.put(self.chain.beacons[r])
            self.stores[a] = store

    def fetch(self, peer, from_round: int):
        store = self.stores[str(peer)]
        for r in range(from_round, self.rounds + 1):
            try:
                yield store.get(r)
            except Exception:
                return

    def run(self) -> StorageScenarioResult:
        from drand_tpu.chain.integrity import IntegrityScanner

        victim_store = self.stores[self.victim]
        injected = inject_storage_faults(victim_store, self.plan, self.rounds)
        scanner = IntegrityScanner(
            victim_store, self.chain.scheme,
            verifier=HostBatchVerifier(self.chain.scheme, self.chain.public),
            genesis_seed=self.chain.genesis_seed, chunk=8,
            beacon_id="chaos-storage")
        # explicit upto: a deleted HEAD row would otherwise shrink the
        # store's own idea of how long the chain is
        report = scanner.scan(mode="full", upto=self.rounds)
        detected = report.faulty_rounds
        all_detected = set(injected).issubset(detected)

        facade = FollowFacade(victim_store, self.chain.scheme.chained,
                              self.chain.genesis_seed)
        peers = [a for a in self.addresses if a != self.victim]
        policy = ResiliencePolicy(
            clock=self.clock, backoff=BackoffPolicy(base=0.2, cap=2.0),
            breakers=BreakerRegistry(clock=self.clock,
                                     scope="chaos-storage"),
            scope="chaos-storage", seed=stable_seed(self.seed, "heal"))
        syncm = SyncManager(
            chain=facade, scheme=self.chain.scheme,
            public_key_bytes=self.chain.public, period=30,
            clock=self.clock, fetch=self.fetch, peers=peers, chunk=8,
            verifier=HostBatchVerifier(self.chain.scheme, self.chain.public),
            resilience=policy)
        unrepaired = syncm.heal(victim_store, report, peers,
                                beacon_id="chaos-storage")
        rescan = scanner.scan(mode="full", upto=self.rounds)

        digests = []
        converged = True
        for a in self.addresses:
            h = hashlib.sha256()
            for r in range(1, self.rounds + 1):
                try:
                    h.update(self.stores[a].get(r).signature)
                except Exception:
                    h.update(b"missing")
                    converged = False
            digests.append(h.hexdigest())
        converged = converged and len(set(digests)) == 1
        return StorageScenarioResult(
            injected=injected, detected_rounds=detected,
            all_detected=all_detected, unrepaired=unrepaired,
            rescan_clean=rescan.clean, converged=converged,
            chain_digest=digests[0])

# ---------------------------------------------------------------------------
# device faults at the backend boundary (the verify-service failure domain):
# PR 6 funneled ALL verification through one resident device pipeline, which
# made one wedged/vanished accelerator a single point of failure for every
# consumer at once (bench r04: 0 r/s, chip unreachable).  These plans fault
# the service's *backend*, never a real chip, so the watchdog → failover →
# probe state machine is testable on any CPU box.
# ---------------------------------------------------------------------------

DEVICE_HANG = "hang"          # dispatch blocks until released (a wedged chip)
DEVICE_RAISE = "raise"        # dispatch raises (chip unreachable)
DEVICE_POISON = "poison"      # dispatch answers with a wrong-shape result


@dataclass
class DeviceFaultPlan:
    """Seeded device-fault schedule.  A fault is a pure function of
    (seed, dispatch#) plus two deterministic failure windows: a
    fake-time flap window [down_from, down_until) and a dispatch-count
    kill switch (every dispatch >= die_after fails, no recovery)."""

    seed: int = 0
    down_from: Optional[float] = None     # fake-time window in which every
    down_until: Optional[float] = None    # dispatch fails with `down_mode`
    down_mode: str = DEVICE_RAISE
    die_after: Optional[int] = None       # dispatch# from which the device
                                          # is dead for good
    raise_p: float = 0.0                  # P(raise) per dispatch, seeded
    poison_p: float = 0.0                 # P(wrong-shape result), seeded

    def fault_at(self, dispatch_no: int, now: float) -> Optional[str]:
        if self.die_after is not None and dispatch_no >= self.die_after:
            return self.down_mode
        if self.down_from is not None and now >= self.down_from \
                and (self.down_until is None or now < self.down_until):
            return self.down_mode
        dice = random.Random(stable_seed(self.seed, "device", dispatch_no))
        if dice.random() < self.raise_p:
            return DEVICE_RAISE
        if dice.random() < self.poison_p:
            return DEVICE_POISON
        return None


class FaultyDeviceBackend:
    """Wrap any verify backend with a DeviceFaultPlan at the service's
    backend boundary.  `release` frees hung dispatches (set it in
    teardown so abandoned watchdog threads exit instead of leaking)."""

    kind = "device"

    def __init__(self, inner, plan: DeviceFaultPlan, clock):
        self.inner = inner
        self.plan = plan
        self.clock = clock
        self.release = threading.Event()
        self.dispatches = 0
        self.faults: List[tuple] = []     # (dispatch#, fault kind)
        self.first_fault_time: Optional[float] = None
        self._lock = threading.Lock()

    def verify_batch(self, rounds, sigs, prev_sigs=None):
        now = self.clock.now()
        with self._lock:
            i = self.dispatches
            self.dispatches += 1
            fault = self.plan.fault_at(i, now)
            if fault is not None:
                self.faults.append((i, fault))
                if self.first_fault_time is None:
                    self.first_fault_time = self.clock.monotonic()
        if fault == DEVICE_HANG:
            # parks until the scenario releases it; the watchdog abandons
            # the dispatch long before, the 600 s cap merely bounds a
            # teardown that forgot to release
            self.release.wait(600)
            raise ConnectionError("device hung (released by teardown)")
        if fault == DEVICE_RAISE:
            raise ConnectionError("device unreachable")
        out = self.inner.verify_batch(rounds, sigs, prev_sigs)
        if fault == DEVICE_POISON:
            return out[:-1]               # wrong shape: one lane short
        return out


@dataclass
class DeviceScenarioResult:
    all_resolved: bool                # zero forever-pending futures
    verdicts_match_host: bool         # identical to a host-only run
    failovers: int
    watchdog_trips: int
    failover_latency: Optional[float]  # fake seconds, fault -> degraded
    deadline: float                   # the watchdog deadline at that time
    repromoted: bool                  # device healthy again after recovery
    device_served_after_recovery: bool
    final_state: str

    @property
    def ok(self) -> bool:
        return (self.all_resolved and self.verdicts_match_host
                and self.failovers >= 1
                and (self.failover_latency is None
                     or self.failover_latency <= self.deadline)
                and self.repromoted and self.device_served_after_recovery)


class DeviceChaosScenario:
    """Mixed live/background workload through a flapping device.

    Timeline (fake seconds from start=1000): healthy traffic, then the
    device enters a raise-on-dispatch flap window at +100, traffic during
    the outage (must fail over, nobody's future may break), recovery at
    +200, canary probe re-promotes, post-recovery traffic runs on the
    device again."""

    def __init__(self, seed: int, rounds: int = 24,
                 chain: Optional[TrueChain] = None,
                 watchdog_floor: float = 30.0, probe_interval: float = 5.0):
        from drand_tpu.crypto.verify_service import VerifyService

        self.seed = seed
        self.rounds = rounds
        self.clock = AutoClock(start=1_000.0)
        self.chain = chain if chain is not None and chain.n >= rounds \
            else TrueChain(n=rounds)
        sch = self.chain.scheme
        self.host = HostBatchVerifier(sch, self.chain.public)
        self.plan = DeviceFaultPlan(seed=stable_seed(seed, "device-flap"),
                                    down_from=1_100.0, down_until=1_200.0,
                                    down_mode=DEVICE_RAISE)
        self.device = FaultyDeviceBackend(
            HostBatchVerifier(sch, self.chain.public), self.plan, self.clock)
        self.svc = VerifyService(clock=self.clock, pad=8,
                                 background_window=0.0,
                                 watchdog_floor=watchdog_floor,
                                 probe_interval=probe_interval)
        self.handle = self.svc.handle(
            sch, self.chain.public, backend=self.device,
            fallback=HostBatchVerifier(sch, self.chain.public))

    def _workload(self):
        """(rounds, sigs, prevs) with seeded forged rounds, so verdict
        parity against the host-only run is a real check, not all-True."""
        dice = random.Random(stable_seed(self.seed, "forge"))
        rounds = list(range(1, self.rounds + 1))
        forged = set(dice.sample(rounds, max(2, self.rounds // 8)))
        sigs, prevs = [], []
        for r in rounds:
            b = self.chain.beacons[r]
            sigs.append(corrupt_signature(b).signature if r in forged
                        else b.signature)
            prevs.append(b.previous_sig)
        return rounds, sigs, prevs

    def run(self) -> DeviceScenarioResult:
        import numpy as np

        rounds, sigs, prevs = self._workload()
        expected = self.host.verify_batch(rounds, sigs, prevs)

        futs = []           # ((lo, hi), future)

        def submit(lo, hi, lane):
            futs.append(((lo, hi), self.handle.submit(
                rounds[lo:hi], sigs[lo:hi], prevs[lo:hi], lane=lane,
                flush_now=True)))

        def settle(timeout=30):
            for _, f in futs:
                f.result(timeout)

        try:
            # phase 1: healthy — device serves both lanes
            submit(0, 8, "background")
            submit(8, 10, "live")
            settle()
            # phase 2: the flap window — mixed traffic during the outage
            self.clock.jump(100.0)        # now 1100: device down
            submit(10, 16, "background")
            submit(16, 18, "live")
            submit(18, 20, "background")
            settle()                      # resolves via host failover
            slot = self.svc._slots[self.handle.key]
            deadline = self.svc._deadline_for(slot)
            failover_latency = None
            if slot.degraded_at is not None \
                    and slot.first_fault_at is not None:
                failover_latency = slot.degraded_at - slot.first_fault_at
            # phase 3: recovery — past the window, the canary re-promotes
            self.clock.jump(150.0)        # now >= 1250: device answers
            repromoted = False
            for _ in range(400):          # real-time wait on the probe
                if slot.state == "healthy":
                    repromoted = True
                    break
                self.clock.jump(self.svc.probe_interval)
                threading.Event().wait(0.05)
            # phase 4: post-recovery traffic runs on the device again
            before = self.device.dispatches
            submit(20, self.rounds, "live")
            settle()
            device_served = self.device.dispatches > before

            all_resolved = all(f.done() for _, f in futs)
            got = np.zeros(self.rounds, dtype=bool)
            for (lo, hi), f in futs:
                got[lo:hi] = f.result(0)
            st = self.svc.stats()
            return DeviceScenarioResult(
                all_resolved=all_resolved,
                verdicts_match_host=bool((got == expected).all()),
                failovers=st["failovers"],
                watchdog_trips=st["watchdog_trips"],
                failover_latency=failover_latency,
                deadline=deadline,
                repromoted=repromoted,
                device_served_after_recovery=device_served,
                final_state=slot.state)
        finally:
            self.device.release.set()
            self.svc.stop()


@dataclass
class SyncFailoverResult:
    converged: bool
    faulty_after_sync: List[int]
    elapsed: float                    # fake seconds spent syncing
    period: float
    degraded: bool                    # the service failed over mid-sync
    device_dispatches: int

    @property
    def ok(self) -> bool:
        return (self.converged and not self.faulty_after_sync
                and self.degraded and self.elapsed <= self.period)


class DeviceFailoverSyncScenario:
    """Kill the device backend mid-catch-up-sync on a live 3-node
    network: node0 holds the true chain, node1 catches up through a
    verify-service handle whose device backend dies for good after
    `die_after` dispatches.  The sync must converge via the host
    failover path before the round deadline (one period of fake time —
    failover is raise-driven here, so it costs retries, not a watchdog
    wait)."""

    def __init__(self, seed: int, rounds: int = 24, period: float = 30.0,
                 die_after: int = 2, chain: Optional[TrueChain] = None):
        from drand_tpu.crypto.verify_service import VerifyService

        self.seed = seed
        self.rounds = rounds
        self.period = period
        self.clock = AutoClock(start=1_000.0)
        self.chain = chain if chain is not None and chain.n >= rounds \
            else TrueChain(n=rounds)
        sch = self.chain.scheme
        self.plan = DeviceFaultPlan(seed=stable_seed(seed, "device-kill"),
                                    die_after=die_after,
                                    down_mode=DEVICE_RAISE)
        self.device = FaultyDeviceBackend(
            HostBatchVerifier(sch, self.chain.public), self.plan, self.clock)
        self.svc = VerifyService(clock=self.clock, pad=8,
                                 background_window=0.0,
                                 watchdog_floor=30.0, probe_interval=5.0)
        self.handle = self.svc.handle(
            sch, self.chain.public, backend=self.device,
            fallback=HostBatchVerifier(sch, self.chain.public))
        self.addresses = ["node0", "node1", "node2"]
        self.stores: Dict[str, MemDBStore] = {}
        self.facades: Dict[str, FollowFacade] = {}
        for a in self.addresses:
            store = MemDBStore(buffer_size=rounds + 8)
            facade = FollowFacade(store, sch.chained, self.chain.genesis_seed)
            if a == "node0":
                for r in range(1, rounds + 1):
                    facade.put(self.chain.beacons[r])
            self.stores[a] = store
            self.facades[a] = facade

    def fetch(self, peer, from_round: int):
        store = self.stores[str(peer)]
        for r in range(from_round, self.rounds + 1):
            try:
                yield store.get(r)
            except Exception:
                return

    def run(self) -> SyncFailoverResult:
        policy = ResiliencePolicy(
            clock=self.clock, backoff=BackoffPolicy(base=0.2, cap=2.0),
            breakers=BreakerRegistry(clock=self.clock,
                                     scope="chaos-device-sync"),
            scope="chaos-device-sync", seed=stable_seed(self.seed, "sync"))
        syncm = SyncManager(
            chain=self.facades["node1"], scheme=self.chain.scheme,
            public_key_bytes=self.chain.public, period=self.period,
            clock=self.clock, fetch=self.fetch,
            peers=["node0", "node2"], chunk=8,
            verifier=self.handle, resilience=policy,
            sync_budget=10_000.0)
        t0 = self.clock.now()
        converged = True
        try:
            try:
                syncm.sync(self.rounds, syncm.peers)
            except Exception:
                converged = False
            faulty = syncm.check_past_beacons(self.rounds)
            elapsed = self.clock.now() - t0
            digests = []
            for a in ("node0", "node1"):
                h = hashlib.sha256()
                for r in range(1, self.rounds + 1):
                    try:
                        h.update(self.stores[a].get(r).signature)
                    except Exception:
                        h.update(b"missing")
                        converged = False
                digests.append(h.hexdigest())
            converged = converged and len(set(digests)) == 1
            st = self.svc.stats()
            return SyncFailoverResult(
                converged=converged, faulty_after_sync=faulty,
                elapsed=elapsed, period=self.period,
                degraded=st["failovers"] >= 1,
                device_dispatches=self.device.dispatches)
        finally:
            self.device.release.set()
            self.svc.stop()


# ---------------------------------------------------------------------------
# multi-device group isolation (ISSUE 11): one group's induced device
# fault must degrade ONLY that group — its chain fails over to a healthy
# sibling group (or host), while every other chain's verdicts, backend
# state and latency history stay untouched.
# ---------------------------------------------------------------------------


class _RuleBackend:
    """Deterministic stub verdict backend (sig == b"sig-<round>") with
    per-backend dispatch accounting, for scheduler-level group scenarios
    that need zero crypto."""

    kind = "device"

    def __init__(self):
        self.calls: List[list] = []

    def verify_batch(self, rounds, sigs, prev_sigs=None):
        import numpy as np
        self.calls.append(list(rounds))
        return np.array([s == b"sig-%d" % r for r, s in zip(rounds, sigs)],
                        dtype=bool)


@dataclass
class GroupIsolationResult:
    all_resolved: bool
    verdicts_match: bool              # every chain == the stub rule
    victim_failed_over: bool          # sibling migration OR host degrade
    victim_final_state: str
    faulted_groups: List[int]         # must be exactly the victim's group
    victim_group: int
    sibling_states: List[str]
    siblings_untouched: bool          # no extra dispatches/latency samples
    migrations: int
    failovers: int

    @property
    def ok(self) -> bool:
        return (self.all_resolved and self.verdicts_match
                and self.victim_failed_over
                and self.faulted_groups == [self.victim_group]
                and all(s == "healthy" for s in self.sibling_states)
                and self.siblings_untouched)


class GroupIsolationScenario:
    """k chains on k device groups; the victim chain's group dies (its
    backend raises on every dispatch from the fault point on).  The
    failover order is group→sibling→host: with a healthy sibling
    available the victim's backend is REBUILT there (never touching the
    host path) and every other group keeps serving undisturbed."""

    def __init__(self, seed: int, chains: int = 4, rounds_per_chain: int = 8,
                 siblings_available: bool = True):
        from drand_tpu.crypto.verify_service import VerifyService

        self.seed = seed
        self.k = chains
        self.n = rounds_per_chain
        self.clock = AutoClock(start=1_000.0)
        self.siblings_available = siblings_available
        self.svc = VerifyService(
            clock=self.clock, pad=8, background_window=0.0,
            watchdog_floor=30.0, probe_interval=5.0,
            device_groups=chains if siblings_available else 1)
        dice = random.Random(stable_seed(seed, "group-isolation"))
        self.victim = dice.randrange(chains)
        self.plan = DeviceFaultPlan(seed=stable_seed(seed, "group-kill"),
                                    die_after=1, down_mode=DEVICE_RAISE)
        self.backends: Dict[int, list] = {i: [] for i in range(chains)}
        self.handles = []
        for i in range(chains):
            self.handles.append(self.svc.handle(
                types.SimpleNamespace(id=f"chaos-chain-{i}"),
                bytes([i + 1]) * 48,
                backend_factory=self._factory(i),
                fallback=_RuleBackend()))
        self.victim_gid0 = self.handles[self.victim].gid

    def _factory(self, i):
        def build(group):
            if i == self.victim and not self.backends[i]:
                # the victim group's device: healthy for dispatch #0,
                # dead for good afterwards (the seeded kill switch)
                b = FaultyDeviceBackend(_RuleBackend(), self.plan,
                                        self.clock)
            else:
                b = _RuleBackend()      # sibling rebuilds land healthy
            self.backends[i].append(b)
            return b
        return build

    def _workload(self, i, phase):
        dice = random.Random(stable_seed(self.seed, "forge", i, phase))
        rounds = list(range(1, self.n + 1))
        forged = set(dice.sample(rounds, 2))
        sigs = [b"sig-%d" % r if r not in forged else b"forged"
                for r in rounds]
        return rounds, sigs, [r in forged for r in rounds]

    def run(self) -> GroupIsolationResult:
        import numpy as np

        futs = []       # (chain, expected_bad, future)
        # phase 1: every chain healthy (the victim's dispatch #0)
        for i, h in enumerate(self.handles):
            rounds, sigs, bad = self._workload(i, 1)
            futs.append((i, bad, h.submit(rounds, sigs, flush_now=True)))
        for _, _, f in futs:
            f.result(30)
        # phase 2: the victim group is dead — mixed lanes across chains
        for i, h in enumerate(self.handles):
            rounds, sigs, bad = self._workload(i, 2)
            lane = "live" if i % 2 else "background"
            futs.append((i, bad, h.submit(rounds, sigs, lane=lane,
                                          flush_now=True)))
        all_resolved = True
        verdicts_match = True
        for i, bad, f in futs:
            try:
                got = f.result(30)
            except Exception:
                all_resolved = False
                continue
            want = np.array([not b for b in bad])
            verdicts_match &= bool((got == want).all())
        st = self.svc.stats()
        victim_slot = self.svc._slots[self.handles[self.victim].key]
        sibling_slots = [self.svc._slots[h.key]
                         for i, h in enumerate(self.handles)
                         if i != self.victim]
        faulted = sorted(g for g, info in st["groups"].items()
                         if info["state"] != "healthy")
        # siblings untouched: each served exactly its own 2 submissions,
        # on its own group, with exactly 2 latency samples
        untouched = all(
            s.state == "healthy" and len(s.latencies) == 2
            and len(self.backends[i][0].calls) == 2
            for s, i in zip(sibling_slots,
                            [i for i in range(self.k) if i != self.victim]))
        self.svc.stop()
        return GroupIsolationResult(
            all_resolved=all_resolved,
            verdicts_match=verdicts_match,
            victim_failed_over=(st["migrations"] >= 1
                                or st["failovers"] >= 1),
            victim_final_state=victim_slot.state,
            faulted_groups=faulted,
            victim_group=self.victim_gid0,
            sibling_states=[s.state for s in sibling_slots],
            siblings_untouched=untouched,
            migrations=st["migrations"],
            failovers=st["failovers"])


# ---------------------------------------------------------------------------
# serving-plane overload (the admission-control target, net/admission.py):
# a public read flood plus one sync-hog peer during live rounds.  Pure
# controller-level simulation — the wire shapes (HTTP 429, gRPC
# RESOURCE_EXHAUSTED) are covered by tests/test_admission.py against real
# servers; this scenario proves the POLICY: reservation, fair share,
# ladder ordering, hysteretic recovery.
# ---------------------------------------------------------------------------


@dataclass
class OverloadResult:
    served_reads: int
    shed_reads: int
    shed_ratio: float
    partials_admitted: int
    partials_p99: float               # critical-class admission wait p99
    period: float
    sheds_well_formed: bool           # every Shed named a reason + retry
    peer_cap_sheds: int               # the hog's over-cap streams refused
    hog_rounds: int
    hog_bound: float                  # fair-share ceiling on hog_rounds
    paced: bool                       # pacing actually engaged
    max_level: int
    bg_pause_at: Optional[float]      # fake time the background lane paused
    first_normal_shed_at: Optional[float]   # first LEVEL-based normal shed
    ladder_ordered: bool              # bg paused strictly before that shed
    bg_resumed: bool
    final_level: int

    @property
    def ok(self) -> bool:
        return (self.served_reads > 0
                and self.shed_reads > 0
                and self.sheds_well_formed
                and self.partials_p99 < self.period
                and self.peer_cap_sheds > 0
                and self.paced
                and self.hog_rounds <= self.hog_bound
                and self.max_level >= 3
                and self.ladder_ordered
                and self.bg_resumed
                and self.final_level == 0)


class OverloadScenario:
    """Read flood + sync-hog peer against one AdmissionController.

    Timeline (fake seconds): a seeded flood of sheddable reads saturates
    the non-critical token pool while two victim peers try to open
    normal-class sync streams (their timed-out waits are the queue-wait
    signal that climbs the ladder) and a hog peer drains a sync stream
    as fast as pacing allows.  Critical partials arrive every second
    throughout and must never wait.  After the flood the ladder must
    step back down to nominal."""

    def __init__(self, seed: int, period: float = 30.0,
                 flood_seconds: int = 40, recover_seconds: int = 45,
                 flood_rate: int = 30):
        from drand_tpu.net.admission import AdmissionController

        self.seed = seed
        self.period = period
        self.flood_seconds = flood_seconds
        self.recover_seconds = recover_seconds
        self.flood_rate = flood_rate
        self.clock = AutoClock(start=1_000.0)
        self.bg_events: List[tuple] = []      # (fake time, paused)
        self.ctrl = AdmissionController(
            clock=self.clock, capacity=16, critical_reserve=4,
            max_streams_per_peer=2, shed_wait=0.5, recover_wait=0.05,
            dwell=4.0, normal_wait=2.0, pace_rate=64.0, pace_burst=16,
            background_hook=lambda paused: self.bg_events.append(
                (self.clock.monotonic(), paused)))

    def run(self) -> OverloadResult:
        from drand_tpu.net.admission import (CLASS_CRITICAL, CLASS_NORMAL,
                                             CLASS_SHEDDABLE, REASON_LEVEL,
                                             REASON_PEER_CAP, Shed)

        ctrl, clock = self.ctrl, self.clock
        rng = random.Random(stable_seed(self.seed, "overload"))
        stop = threading.Event()
        state = {"served": 0, "shed": 0, "malformed": 0, "peer_cap": 0,
                 "partials": 0, "hog_rounds": 0, "paced": False}
        state_lock = threading.Lock()
        holds: List[tuple] = []               # (release_at, ticket) heap-ish

        def well_formed(s: Shed) -> bool:
            return (s.retry_after > 0 and s.cls in str(s)
                    and s.reason in (REASON_LEVEL, "capacity",
                                     REASON_PEER_CAP))

        def note_shed(s: Shed, peer_cap: bool = False) -> None:
            with state_lock:
                state["shed"] += 1
                if peer_cap:
                    state["peer_cap"] += 1
                if not well_formed(s):
                    state["malformed"] += 1

        # -- the hog: 2 granted streams + 1 refused, then drain flat out
        def hog():
            tickets = []
            for _ in range(2):
                try:
                    tickets.append(ctrl.admit(CLASS_NORMAL, peer="hog",
                                              stream=True))
                except Shed as s:
                    note_shed(s)
            try:
                ctrl.admit(CLASS_NORMAL, peer="hog", stream=True)
            except Shed as s:           # over the per-peer fair-share cap
                note_shed(s, peer_cap=isinstance(s, Shed)
                          and s.reason == REASON_PEER_CAP)
            t = tickets[0] if tickets else None
            while t is not None and not stop.is_set():
                waited = t.pace(8)
                with state_lock:
                    state["hog_rounds"] += 8
                    if waited > 0:
                        state["paced"] = True
            for t in tickets:
                t.release()

        # -- victims: keep trying to open sync streams; their timed-out
        #    waits feed the ladder's p99 signal
        def victim(name):
            while not stop.is_set():
                try:
                    t = ctrl.admit(CLASS_NORMAL, peer=name, stream=True)
                    t.release()
                except Shed as s:
                    note_shed(s)
                threading.Event().wait(0.01)

        threads = [threading.Thread(target=hog, daemon=True, name="ov-hog")]
        threads += [threading.Thread(target=victim, args=(f"victim{i}",),
                                     daemon=True, name=f"ov-victim{i}")
                    for i in range(2)]
        # a third normal stream so pacing sees >1 distinct peers even
        # while the victims are being shed
        base_stream = ctrl.admit(CLASS_NORMAL, peer="steady", stream=True)
        for th in threads:
            th.start()

        def step(flood: bool) -> None:
            now = clock.monotonic()
            holds[:] = [(at, t) for at, t in holds
                        if at > now or (t.release() and False)]
            arrivals = rng.randrange(self.flood_rate // 2,
                                     self.flood_rate * 2) if flood else 1
            for i in range(arrivals):
                ticket, s = ctrl.try_admit(CLASS_SHEDDABLE,
                                           peer=f"edge{i % 8}")
                if ticket is not None:
                    with state_lock:
                        state["served"] += 1
                    holds.append((now + rng.uniform(2.0, 5.0), ticket))
                else:
                    note_shed(s)
            # one partial per second: the thing overload must never cost
            pt = ctrl.admit(CLASS_CRITICAL, peer="signer")
            with state_lock:
                state["partials"] += 1
            pt.release()
            clock.jump(1.0)
            # give the waiter threads a real-time slice to observe it
            threading.Event().wait(0.012)

        for _ in range(self.flood_seconds):
            step(flood=True)
        flood_end = clock.monotonic()
        stop.set()
        for th in threads:
            th.join(timeout=10)
        for _, t in holds:
            t.release()
        holds.clear()
        base_stream.release()
        for _ in range(self.recover_seconds):
            step(flood=False)

        snap = ctrl.snapshot()
        partials_p99 = ctrl.wait_p99(CLASS_CRITICAL)
        max_level = max((lvl for _, lvl in snap["transitions"]), default=0)
        bg_pause_at = next((t for t, paused in self.bg_events if paused),
                           None)
        first_normal_level_shed = next(
            (t for t, cls, reason in ctrl._shed_log
             if cls == CLASS_NORMAL and reason == REASON_LEVEL), None)
        ladder_ordered = (first_normal_level_shed is None
                          or (bg_pause_at is not None
                              and bg_pause_at < first_normal_level_shed))
        # fair-share ceiling: two burst allowances plus the SHARED pace
        # budget for the whole flood window (generous: the hog only ever
        # gets a fraction of pace_rate while others stream)
        elapsed = flood_end - 1_000.0
        hog_bound = (2 * self.ctrl.pace_burst
                     + self.ctrl.pace_rate * elapsed + 8)
        with state_lock:
            served, shed = state["served"], state["shed"]
            return OverloadResult(
                served_reads=served, shed_reads=shed,
                shed_ratio=shed / max(1, served + shed),
                partials_admitted=state["partials"],
                partials_p99=partials_p99, period=self.period,
                sheds_well_formed=state["malformed"] == 0 and shed > 0,
                peer_cap_sheds=state["peer_cap"],
                hog_rounds=state["hog_rounds"], hog_bound=hog_bound,
                paced=state["paced"],
                max_level=max_level, bg_pause_at=bg_pause_at,
                first_normal_shed_at=first_normal_level_shed,
                ladder_ordered=ladder_ordered
                and first_normal_level_shed is not None,
                bg_resumed=bool(self.bg_events)
                and self.bg_events[-1][1] is False,
                final_level=snap["level"])


# ---------------------------------------------------------------------------
# DKG/reshare lifecycle chaos (ISSUE 12): crash-safety of the one plane the
# earlier robustness passes never covered.  `_LocalDkgNet` is an in-process
# ProtocolClient stub routing the full DKG/beacon RPC surface between REAL
# BeaconProcesses by address — real setup plane, real EchoBroadcast boards,
# real session journal and pending-transition ledger on real (tmpdir)
# FileStores, zero gRPC.  `DkgLifecycleHarness` runs an n-node network of
# them on one FakeClock; the scenarios below crash/restart nodes at the
# nastiest points of the lifecycle.
# ---------------------------------------------------------------------------


class _LocalDkgNet:
    """ProtocolClient stand-in: routes by address with kill switches, an
    inbound-DKG drop gate (a node that can send but not receive — the
    hang that turns into a mid-deal crash), and a tap recording every
    routed DKG packet (stale-bundle tests replay from it)."""

    resilience = None           # BeaconProcess falls back to cfg's policy

    def __init__(self):
        self.procs: Dict[str, object] = {}
        self.down: set = set()
        self.drop_dkg_to: set = set()
        self.fail_push_to: set = set()    # push_dkg_info raises (partial
                                          # group arming, ISSUE 12)
        self.taps: List[tuple] = []       # (dest addr, DKGPacket)
        self._lock = threading.Lock()

    def register(self, bp) -> None:
        with self._lock:
            self.procs[bp.pair.public.addr] = bp
            self.down.discard(bp.pair.public.addr)

    def kill(self, addr: str) -> None:
        with self._lock:
            self.down.add(addr)

    def _bp(self, peer):
        addr = getattr(peer, "address", None) or str(peer)
        with self._lock:
            if addr in self.down:
                raise ConnectionError(f"{addr} is down")
            bp = self.procs.get(addr)
        if bp is None:
            raise ConnectionError(f"no node at {addr}")
        return bp

    # -- the ProtocolClient surface BeaconProcess consumes -------------------

    def get_identity(self, peer, beacon_id: str = "", deadline=None,
                     timeout=None):
        from drand_tpu.net import convert
        from drand_tpu.protos import drand_pb2 as pb
        ident = self._bp(peer).pair.public
        return pb.IdentityResponse(
            address=ident.addr, key=ident.key, tls=ident.tls,
            signature=ident.signature or b"",
            metadata=convert.metadata(beacon_id),
            schemeName=ident.scheme.id)

    def signal_dkg_participant(self, peer, packet, timeout=None,
                               deadline=None):
        self._bp(peer).signal_dkg_participant(packet)

    def push_dkg_info(self, peer, packet, timeout=None):
        bp = self._bp(peer)
        with self._lock:
            if bp.pair.public.addr in self.fail_push_to:
                raise ConnectionError(
                    f"{bp.pair.public.addr} refused the group push")
        bp.push_dkg_info(packet)

    def broadcast_dkg(self, peer, packet):
        bp = self._bp(peer)
        addr = bp.pair.public.addr
        with self._lock:
            self.taps.append((addr, packet))
            if addr in self.drop_dkg_to:
                return          # delivered nowhere: inbound partition
        bp.broadcast_dkg(packet)

    def partial_beacon(self, peer, packet, deadline=None, timeout=None):
        bp = self._bp(peer)
        try:
            bp.process_partial(packet)
        except ValueError:
            pass                # stale/window rejections are per-protocol

    def sync_chain(self, peer, from_round: int, beacon_id: str = ""):
        # peers serve nothing: the lifecycle scenarios run thr == n, so
        # the chain only advances in lockstep and nobody ever NEEDS sync
        self._bp(peer)
        return iter(())


class DkgLifecycleHarness:
    """n real BeaconProcesses over one _LocalDkgNet + shared FakeClock,
    each with its own tmpdir FileStore (journal, staged files, sqlite
    chain).  thr == n, so every node's partial is load-bearing: a node
    signing any round with the wrong share stalls the chain — 'no
    invalid partials' is asserted by progress itself."""

    SECRET = b"lifecycle-secret"

    def __init__(self, root: str, n: int = 3, period: int = 30,
                 clock=None, dkg_timeout: int = 4, reshare_offset: int = 45,
                 db_engine: str = "sqlite"):
        self.root = str(root)
        self.n = n
        self.period = period
        self.dkg_timeout = dkg_timeout
        self.reshare_offset = reshare_offset
        self.db_engine = db_engine
        self.clock = clock if clock is not None \
            else FakeClock(start=1_700_000_000.0)
        self.net = _LocalDkgNet()
        self.addrs = [f"127.0.0.1:{7100 + i}" for i in range(n)]
        self.bps: Dict[int, object] = {}
        self.cfgs: Dict[int, object] = {}
        for i in range(n):
            self.build_process(i)

    def build_process(self, i: int):
        """(Re)create node i's BeaconProcess over its on-disk state —
        construction + load() IS the restart path under test."""
        from drand_tpu.core.beacon_process import BeaconProcess
        from drand_tpu.core.config import Config
        from drand_tpu.crypto.schemes import get_scheme_by_id_with_default
        from drand_tpu.key.keys import new_keypair
        from drand_tpu.key.store import FileStore
        from drand_tpu.log import Logger

        folder = os.path.join(self.root, f"n{i}")
        cfg = Config(folder=folder, clock=self.clock,
                     db_engine=self.db_engine, use_device_verifier=False,
                     dkg_timeout=self.dkg_timeout, dkg_kickoff_grace=0.0,
                     reshare_offset=self.reshare_offset, sync_budget=5.0,
                     insecure=True)
        fstore = FileStore(folder, "default")
        try:
            pair = fstore.load_keypair()
        except FileNotFoundError:
            pair = new_keypair(self.addrs[i],
                               get_scheme_by_id_with_default(""),
                               tls=False, seed=b"lifecycle-%d" % i)
            fstore.save_keypair(pair)
        bp = BeaconProcess(cfg, fstore, "default", pair, self.net,
                           Logger(f"n{i}"))
        self.net.register(bp)
        self.bps[i] = bp
        self.cfgs[i] = cfg
        return bp

    # -- sessions ------------------------------------------------------------

    def run_dkg(self, threshold: Optional[int] = None, secret: bytes = b"",
                setup_timeout: float = 30.0, leader: int = 0,
                start_beacons: bool = True, timeout: float = 120.0):
        """Full networked DKG through the real control-plane entry points
        (leader thread + follower threads, like the daemon's InitDKG)."""
        from drand_tpu.crypto.schemes import get_scheme_by_id_with_default
        from drand_tpu.net import Peer as NetPeer

        secret = secret or self.SECRET
        thr = threshold if threshold is not None else self.n
        results: Dict[int, object] = {}
        errors: List[tuple] = []

        def lead():
            try:
                results[leader] = self.bps[leader].init_dkg_leader(
                    n_nodes=self.n, threshold=thr, period=self.period,
                    catchup_period=5, secret=secret,
                    setup_timeout=setup_timeout,
                    scheme=get_scheme_by_id_with_default(""))
            except Exception as e:
                errors.append((leader, e))

        def follow(i):
            try:
                results[i] = self.bps[i].join_dkg(
                    leader=NetPeer(self.addrs[leader]), secret=secret,
                    setup_timeout=setup_timeout)
            except Exception as e:
                errors.append((i, e))

        lt = threading.Thread(target=lead, daemon=True, name="dkg-leader")
        lt.start()
        self._await_setup(self.bps[leader])
        fts = [threading.Thread(target=follow, args=(i,), daemon=True,
                                name=f"dkg-follow-{i}")
               for i in range(self.n) if i != leader]
        for t in fts:
            t.start()
        for t in [lt] + fts:
            t.join(timeout=timeout)
        if errors:
            raise RuntimeError(f"dkg failed: {errors}")
        group = results[leader]
        if start_beacons:
            for i in range(self.n):
                self.bps[i].start_beacon(catchup=False)
        return group

    def run_reshare(self, old_group, threshold: Optional[int] = None,
                    secret: bytes = b"", setup_timeout: float = 30.0,
                    leader: int = 0, timeout: float = 120.0):
        from drand_tpu.net import Peer as NetPeer

        secret = secret or self.SECRET
        thr = threshold if threshold is not None else self.n
        results: Dict[int, object] = {}
        errors: List[tuple] = []

        def lead():
            try:
                results[leader] = self.bps[leader].init_reshare_leader(
                    old_group, n_nodes=self.n, threshold=thr,
                    secret=secret, setup_timeout=setup_timeout)
            except Exception as e:
                errors.append((leader, e))

        def follow(i):
            try:
                results[i] = self.bps[i].join_reshare(
                    leader=NetPeer(self.addrs[leader]),
                    old_group=self.bps[i].group or old_group,
                    secret=secret, setup_timeout=setup_timeout)
            except Exception as e:
                errors.append((i, e))

        lt = threading.Thread(target=lead, daemon=True, name="resh-leader")
        lt.start()
        self._await_setup(self.bps[leader])
        fts = [threading.Thread(target=follow, args=(i,), daemon=True,
                                name=f"resh-follow-{i}")
               for i in range(self.n) if i != leader]
        for t in fts:
            t.start()
        for t in [lt] + fts:
            t.join(timeout=timeout)
        if errors:
            raise RuntimeError(f"reshare failed: {errors}")
        return results[leader]

    @staticmethod
    def _await_setup(bp, timeout: float = 30.0) -> None:
        """Block (real time) until the leader's setup manager is up, so
        follower signals never hit the retry/backoff path (whose sleeps
        ride the frozen fake clock)."""
        import time as _t
        deadline = _t.monotonic() + timeout
        while bp._setup_manager is None:
            if _t.monotonic() >= deadline:
                raise TimeoutError("leader setup never came up")
            _t.sleep(0.01)

    # -- round production ----------------------------------------------------

    def set_genesis(self, group) -> None:
        self.clock.set_time(group.genesis_time)

    def advance_round(self) -> None:
        self.clock.advance(self.period)

    def wait_all(self, round_: int, timeout: float = 120.0) -> List[object]:
        out = []
        for i in sorted(self.bps):
            bp = self.bps[i]
            if bp.handler is None:
                continue
            b = bp.handler.chain.wait_for_round(round_, timeout,
                                                scheduled_time=True)
            assert b is not None, f"node {i} never reached round {round_}"
            out.append(b)
        return out

    # -- crash / restart -----------------------------------------------------

    def crash(self, i: int, hard: bool = False):
        """Process death.  `hard=False` also runs bp.stop() to reap the
        beacon/sync threads (we share one interpreter with the 'dead'
        process) — stop() never touches the journal/ledger/key files, so
        the DISK is exactly what the dead process last wrote.  `hard=True`
        skips stop() entirely: required when the victim dies MID-SESSION,
        where stop()'s board teardown would let the session thread unwind
        and overwrite the journal a real crash leaves behind."""
        bp = self.bps.pop(i)
        self.net.kill(self.addrs[i])
        if not hard:
            bp.stop()
            self.cfgs[i].stop_verify_service()
        return bp

    def restart(self, i: int, start: bool = True):
        bp = self.build_process(i)
        loaded = bp.load()
        if loaded and start:
            bp.start_beacon(catchup=True)
        return bp, loaded

    def stop_all(self) -> None:
        for i in list(self.bps):
            try:
                self.bps[i].stop()
            except Exception:
                pass
        for cfg in self.cfgs.values():
            try:
                cfg.stop_verify_service()
            except Exception:
                pass


@dataclass
class ReshareCrashResult:
    converged: bool                  # chain advanced through the handover
    same_public_key: bool            # collective key byte-identical
    all_rounds_verify: bool          # every beacon verifies under that key
    old_state_served_after_restart: bool   # active files untouched by crash
    rearm_action: str                # recovery verdict at restart ("rearm")
    pending_before_transition: bool  # ledger present after restart
    committed_after_transition: bool  # ledger gone + active == staged
    head: int

    @property
    def ok(self) -> bool:
        return (self.converged and self.same_public_key
                and self.all_rounds_verify
                and self.old_state_served_after_restart
                and self.rearm_action == "rearm"
                and self.pending_before_transition
                and self.committed_after_transition)


class ReshareCrashScenario:
    """THE headline: crash between reshare success and the transition
    round, restart, commit from the ledger, chain continues under the
    SAME collective public key with no invalid partials.

    3 nodes, thr = 3 (every partial load-bearing).  Rounds 1-2 under the
    old group; reshare lands (staged files + ledger everywhere); the
    victim crashes in the success→transition window; restart recovery
    re-arms the swap from the ledger; rounds 3 (old shares — proof the
    old share survived), 4 (the transition round: handler swap + ledger
    commit) and 5 (steady state under the new shares) must all form."""

    def __init__(self, seed: int, root: str, victim: Optional[int] = None):
        self.seed = seed
        self.root = root
        dice = random.Random(stable_seed(seed, "reshare-crash"))
        # any node but the reshare leader (0) can be the victim; the
        # leader's session thread would die with it mid-protocol
        self.victim = victim if victim is not None \
            else dice.randrange(1, 3)

    def run(self) -> ReshareCrashResult:
        h = DkgLifecycleHarness(self.root, n=3, period=30,
                                reshare_offset=45)
        try:
            old_group = h.run_dkg()
            old_key = old_group.public_key.key()
            h.set_genesis(old_group)
            h.wait_all(1)
            h.advance_round()
            h.wait_all(2)

            new_group = h.run_reshare(old_group)
            same_key = new_group.public_key.key() == old_key
            transition_round = (
                (new_group.transition_time - new_group.genesis_time)
                // new_group.period + 1)

            # ---- the crash window: reshare succeeded, transition not yet
            victim_fs = h.bps[self.victim].fs
            staged_group = victim_fs.load_group(staged=True)
            h.crash(self.victim)
            # the dead node's ACTIVE state must still be the old epoch
            old_served = (victim_fs.load_group().hash() == old_group.hash()
                          and staged_group is not None
                          and staged_group.hash() == new_group.hash())

            # ---- restart: recovery must re-arm the swap from the ledger
            bp, loaded = h.restart(self.victim, start=False)
            pending_before = bp.journal.load_pending() is not None
            rearm = "rearm" if (loaded and pending_before
                                and bp._armed_transition is not None) \
                else "other"
            if loaded:
                bp.start_beacon(catchup=True)

            # ---- pre-transition round: old shares must still sign
            h.advance_round()
            h.wait_all(3)
            # ---- the transition round: swap + ledger commit
            h.advance_round()
            h.wait_all(4)
            # ---- steady state under the new shares
            h.advance_round()
            h.wait_all(5)

            committed = (bp.journal.load_pending() is None
                         and victim_fs.load_group().hash()
                         == new_group.hash()
                         and victim_fs.load_group(staged=True) is None)

            # every stored round verifies under the (unchanged) key
            scheme = old_group.scheme
            pub = scheme.key_group.from_bytes(old_key)
            store = h.bps[self.victim].handler.chain.store
            all_ok = True
            prev = old_group.get_genesis_seed() if scheme.chained else None
            for r in range(1, 6):
                b = store.get(r)
                msg = scheme.digest_beacon(r, prev if scheme.chained
                                           else None)
                if not scheme.verify(pub, msg, b.signature):
                    all_ok = False
                prev = b.signature
            head = store.last().round
            assert transition_round == 4, transition_round
            return ReshareCrashResult(
                converged=head >= 5,
                same_public_key=same_key,
                all_rounds_verify=all_ok,
                old_state_served_after_restart=old_served,
                rearm_action=rearm,
                pending_before_transition=pending_before,
                committed_after_transition=committed,
                head=head)
        finally:
            h.stop_all()


@dataclass
class DkgFailureResult:
    first_attempt_failed: bool
    status_failed_not_wedged: bool   # DKG_FAILED, not IN_PROGRESS/WAITING
    stale_bundle_rejected: bool
    staged_clean: bool               # no staged files left behind
    retry_succeeded: bool
    detail: str = ""

    @property
    def ok(self) -> bool:
        return (self.first_attempt_failed and self.status_failed_not_wedged
                and self.stale_bundle_rejected and self.staged_clean
                and self.retry_succeeded)


class LeaderCrashSetupScenario:
    """Leader crash DURING setup: followers' signal/identity fetches time
    out on their budget, unwind to DKG_FAILED (steady, serveable state —
    never a wedged WAITING), and a retry against a live leader
    succeeds."""

    def __init__(self, seed: int, root: str):
        self.seed = seed
        self.root = root

    def run(self) -> DkgFailureResult:
        from drand_tpu.core.beacon_process import (DKG_DONE, DKG_FAILED)
        from drand_tpu.net import Peer as NetPeer

        h = DkgLifecycleHarness(self.root, n=3,
                                clock=AutoClock(start=1_700_000_000.0))
        try:
            # the leader is down before anyone signals
            h.net.kill(h.addrs[0])
            failed = []
            for i in (1, 2):
                try:
                    h.bps[i].join_dkg(leader=NetPeer(h.addrs[0]),
                                      secret=h.SECRET, setup_timeout=10.0)
                except Exception:
                    failed.append(i)
            status_ok = all(h.bps[i].dkg_status == DKG_FAILED
                            for i in (1, 2))
            staged_clean = all(
                h.bps[i].fs.load_group(staged=True) is None for i in (1, 2))
            # leader comes back: the SAME follower processes retry
            h.net.register(h.bps[0])
            group = h.run_dkg(start_beacons=False)
            retry_ok = (group is not None
                        and all(h.bps[i].dkg_status == DKG_DONE
                                for i in range(3)))
            return DkgFailureResult(
                first_attempt_failed=failed == [1, 2],
                status_failed_not_wedged=status_ok,
                stale_bundle_rejected=True,   # n/a: no session ever started
                staged_clean=staged_clean,
                retry_succeeded=retry_ok)
        finally:
            h.stop_all()


class DealCrashRestartScenario:
    """Node crash-restart mid-deal-phase: the victim's inbound DKG path
    is partitioned (it deals, then hangs collecting), the process dies
    there, and the restart must (a) finish the journaled session as
    aborted → DKG_FAILED, (b) reject the dead epoch's bundles by nonce,
    and (c) complete a fresh DKG with everyone restarted."""

    def __init__(self, seed: int, root: str):
        self.seed = seed
        self.root = root

    def run(self) -> DkgFailureResult:
        import time as _t

        from drand_tpu.core import dkg_journal as J
        from drand_tpu.core.beacon_process import (DKG_DONE, DKG_FAILED)
        from drand_tpu.net import Peer as NetPeer

        h = DkgLifecycleHarness(self.root, n=3)
        victim = 2
        try:
            h.net.drop_dkg_to.add(h.addrs[victim])
            errors: List[tuple] = []

            def lead():
                try:
                    from drand_tpu.crypto.schemes import \
                        get_scheme_by_id_with_default
                    h.bps[0].init_dkg_leader(
                        n_nodes=3, threshold=2, period=30,
                        catchup_period=5, secret=h.SECRET,
                        setup_timeout=30.0,
                        scheme=get_scheme_by_id_with_default(""))
                except Exception as e:
                    errors.append((0, e))

            def follow(i):
                try:
                    h.bps[i].join_dkg(leader=NetPeer(h.addrs[0]),
                                      secret=h.SECRET, setup_timeout=30.0)
                except Exception as e:
                    errors.append((i, e))

            threads = [threading.Thread(target=lead, daemon=True)]
            lt = threads[0]
            lt.start()
            h._await_setup(h.bps[0])
            for i in (1, victim):
                t = threading.Thread(target=follow, args=(i,), daemon=True)
                threads.append(t)
                t.start()

            # wait (real time) until the victim's journal shows the deal
            # phase — the exact point the "process" dies
            deadline = _t.monotonic() + 60
            vic_journal = h.bps[victim].journal
            while True:
                rec = vic_journal.load_session()
                if rec is not None and rec.phase == J.PHASE_DEAL:
                    break
                if _t.monotonic() >= deadline:
                    raise TimeoutError("victim never reached deal phase")
                _t.sleep(0.02)
            dead_nonce = bytes.fromhex(rec.nonce)
            # HARD crash: the session thread must stay parked exactly
            # where the process died — bp.stop() would tear the board
            # down and let it unwind/overwrite the journal
            h.crash(victim, hard=True)

            # ---- restart the victim FIRST (the journal still says
            # outcome=running, the honest crash artifact): recovery must
            # finish the session as aborted → DKG_FAILED, not a wedge
            from drand_tpu.core.beacon_process import BeaconProcess
            bp2, loaded = h.restart(victim, start=False)
            rec2 = bp2.journal.load_session()
            status_ok = (not loaded
                         and bp2.dkg_status == DKG_FAILED
                         and rec2 is not None
                         and rec2.outcome == J.ABORTED)

            # a straggler replays a bundle from the dead epoch.  The tap
            # may not have caught one yet (the crash races the first deal
            # fan-out), but the SURVIVORS' sessions keep broadcasting the
            # dead epoch — poll for a tapped packet before replaying.
            stale = None
            poll_deadline = _t.monotonic() + 30
            while stale is None and _t.monotonic() < poll_deadline:
                with h.net._lock:
                    stale = next(
                        (p for a, p in h.net.taps
                         if BeaconProcess._packet_nonce(p) == dead_nonce),
                        None)
                if stale is None:
                    _t.sleep(0.05)
            rejected = False
            if stale is not None:
                try:
                    bp2.broadcast_dkg(stale)
                except ValueError:
                    rejected = True

            # unwind the survivors (and the abandoned victim thread):
            # jump fake time past every phase window
            for _ in range(8):
                h.clock.advance(h.dkg_timeout + 5)
                _t.sleep(0.05)
            for t in threads:
                t.join(timeout=90)

            # ---- everyone restarts; a fresh session must succeed
            h.net.drop_dkg_to.clear()
            for i in (0, 1):
                if i in h.bps:
                    h.crash(i)
                h.restart(i, start=False)
            group = h.run_dkg(threshold=2, secret=b"fresh-after-crash",
                              start_beacons=False)
            retry_ok = (group is not None
                        and all(h.bps[i].dkg_status == DKG_DONE
                                for i in range(3)))
            staged_clean = all(
                h.bps[i].fs.load_group(staged=True) is None
                for i in range(3))
            return DkgFailureResult(
                first_attempt_failed=True,
                status_failed_not_wedged=status_ok,
                stale_bundle_rejected=rejected,
                staged_clean=staged_clean,
                retry_succeeded=retry_ok,
                detail=f"dead epoch {dead_nonce.hex()[:16]}")
        finally:
            h.stop_all()


# ---------------------------------------------------------------------------
# Handel committee chaos (beacon/handel.py; ISSUE 13)
# ---------------------------------------------------------------------------


@dataclass
class HandelByzantineResult:
    """Verdict of one seeded committee run."""
    n: int
    n_honest: int
    threshold: int
    honest_complete: int              # honest sessions that hit threshold
    ticks_used: int
    level_budget: int
    byz_behaviors: Dict[int, str] = field(default_factory=dict)
    demotions: Dict[int, List[int]] = field(default_factory=dict)
    polled_after_demotion: List[tuple] = field(default_factory=list)
    recovered_valid: bool = False
    full_weights: List[int] = field(default_factory=list)
    digest: str = ""

    @property
    def ok(self) -> bool:
        return (self.honest_complete == self.n_honest
                and self.ticks_used <= self.level_budget
                and not self.polled_after_demotion
                and self.recovered_valid)


class HandelByzantineScenario:
    """Seeded Byzantine committee on the Handel overlay (FakeClock, zero
    network I/O, real threshold-BLS crypto).

    Honest members run real `HandelSession`s against a shared loopback;
    Byzantine members run NO session — each tick the scenario injects
    their seeded misbehavior directly at honest targets:

      * ``invalid``    — candidates carrying partials with forged sig
                         bytes (verify fails)
      * ``equivocate`` — a DIFFERENT forged candidate per tick (latest
                         wins per sender, so memory stays bounded while
                         the verify window keeps re-paying until the
                         demotion limit)
      * ``outofblock`` — candidates claiming signers outside the level's
                         mirror block (structural violation)
      * ``silent``     — sends nothing at all (the tree must route
                         around the hole)

    Assertions (HandelByzantineResult.ok): every honest session reaches
    the threshold within the LEVEL BUDGET (levels x level_ticks), no
    honest node polls a peer after demoting it, and the recovered group
    signature verifies against the collective key.  Same seed => same
    digest."""

    BEHAVIORS = ("invalid", "equivocate", "outofblock", "silent")

    def __init__(self, seed: int, n: int = 24, threshold: int = 13,
                 n_byzantine: int = 6, scheme_id: str =
                 "pedersen-bls-chained"):
        from drand_tpu.beacon import handel as H
        from drand_tpu.crypto import tbls
        from drand_tpu.crypto.host.params import R

        assert n - n_byzantine >= threshold, "honest quorum must exist"
        self.H = H
        self.seed = seed
        self.n = n
        self.threshold = threshold
        self.scheme = scheme_from_name(scheme_id)
        self.rng = random.Random(stable_seed(seed, "handel"))
        # deterministic polynomial => deterministic digest across runs
        self.poly = tbls.PriPoly(
            [self.rng.randrange(R) for _ in range(threshold)])
        self.pub = self.poly.commit(self.scheme.key_group)
        # Byzantine assignment: seeded sample, behaviors round-robin
        self.byzantine = sorted(self.rng.sample(range(n), n_byzantine))
        self.behaviors = {b: self.BEHAVIORS[i % len(self.BEHAVIORS)]
                          for i, b in enumerate(self.byzantine)}
        self.honest = [i for i in range(n) if i not in self.behaviors]
        self.cfg = H.HandelConfig(min_group=2, fanout=4, window=32,
                                  bad_limit=2)

    # -- misbehavior ---------------------------------------------------------

    def _forged(self, byz: int, variant: int) -> bytes:
        sig_len = 96 if self.scheme.sig_group.point_len == 96 else 48
        body = bytes(self.rng.randrange(256) for _ in range(sig_len))
        return byz.to_bytes(2, "big") + body

    def _inject(self, sessions, demote_ticks, tick: int) -> None:
        """One tick of Byzantine traffic, seeded and order-stable."""
        H = self.H
        for byz in self.byzantine:
            kind = self.behaviors[byz]
            if kind == "silent":
                continue
            # each byz node hits a seeded sample of its mirror partners
            for level in range(1, H.num_levels(self.n) + 1):
                targets = [t for t in H.level_block(self.n, byz, level)
                           if t in sessions]
                if not targets:
                    continue
                tgt = targets[self.rng.randrange(len(targets))]
                recv_level = level     # symmetric blocks (mirror law)
                if kind == "invalid":
                    agg = H.Aggregate({byz: self._forged(byz, 0)})
                elif kind == "equivocate":
                    agg = H.Aggregate({byz: self._forged(byz, tick)})
                else:   # outofblock: claim a signer the level can't hold
                    outside = (max(H.level_block(self.n, tgt, recv_level))
                               + 1) % self.n
                    agg = H.Aggregate({byz: self._forged(byz, 0),
                                       outside: self._forged(outside, 0)})
                sessions[tgt].receive(recv_level, byz, agg)

    # -- the run -------------------------------------------------------------

    def run(self) -> HandelByzantineResult:
        from drand_tpu.beacon.chainstore import HostPartialVerifier
        from drand_tpu.crypto import tbls

        H = self.H
        prev = b"\x21" * 32
        msg = self.scheme.digest_beacon(1, prev)
        partials = {i: tbls.sign_partial(self.scheme, self.poly.eval(i),
                                         msg)
                    for i in self.honest}
        inbox: List[tuple] = []
        sessions: Dict[int, object] = {}
        done: Dict[int, Dict[int, bytes]] = {}
        demote_ticks: Dict[int, Dict[int, int]] = {i: {}
                                                   for i in self.honest}
        tick_now = {"t": 0}

        def sender(me):
            def send(peer, level, agg):
                inbox.append((peer, level, me,
                              H.Aggregate(dict(agg.partials))))
            return send

        for i in self.honest:
            sessions[i] = H.HandelSession(
                self.cfg, self.n, i, self.threshold, 1, prev, msg,
                HostPartialVerifier(self.scheme, self.pub),
                send=sender(i),
                on_complete=(lambda i: lambda parts:
                             done.__setitem__(i, parts))(i),
                on_demote=(lambda i: lambda peer:
                           demote_ticks[i].setdefault(peer,
                                                      tick_now["t"]))(i))
            sessions[i].add_own(partials[i])

        budget = self.cfg.level_budget(self.n)
        ticks_used = budget
        for tick in range(budget):
            tick_now["t"] = tick
            if len(done) == len(self.honest):
                ticks_used = tick
                break
            self._inject(sessions, demote_ticks, tick)
            msgs, inbox[:] = inbox[:], []
            for tgt, lvl, snd, agg in msgs:
                if tgt in sessions:
                    sessions[tgt].receive(lvl, snd, agg)
            for s in sessions.values():
                s.tick()
        else:
            if len(done) == len(self.honest):
                ticks_used = budget

        # demoted peers must stop being polled: any send AT or AFTER the
        # demotion tick (+1 slack: the demotion may land mid-tick, after
        # this tick's send pass already fired) is a violation
        polled_after = []
        for i in self.honest:
            for peer, when in demote_ticks[i].items():
                late = [t for t in sessions[i].sends_to(peer)
                        if t > when]
                if late:
                    polled_after.append((i, peer, late[:3]))

        recovered_valid = False
        digest = ""
        if done:
            first = sorted(done)[0]
            good = list(done[first].values())
            try:
                sig = tbls.recover(self.scheme, self.pub, msg,
                                   good[: self.threshold], self.threshold,
                                   self.n, verify_each=False)
                recovered_valid = self.scheme.verify_beacon(
                    self.scheme.key_group.to_bytes(
                        self.pub.public_key()), 1, prev, sig)
                h = hashlib.sha256()
                for idx in sorted(done[first]):
                    h.update(idx.to_bytes(2, "big"))
                    h.update(done[first][idx])
                h.update(sig)
                digest = h.hexdigest()
            except ValueError:
                pass

        return HandelByzantineResult(
            n=self.n, n_honest=len(self.honest), threshold=self.threshold,
            honest_complete=len(done), ticks_used=ticks_used,
            level_budget=budget, byz_behaviors=dict(self.behaviors),
            demotions={i: sorted(demote_ticks[i]) for i in self.honest
                       if demote_ticks[i]},
            polled_after_demotion=polled_after,
            recovered_valid=recovered_valid,
            full_weights=[len(sessions[i].verified)
                          for i in sorted(sessions)],
            digest=digest)


# ---------------------------------------------------------------------------
# Multi-tenant noisy neighbor (core/tenancy.py, ISSUE 15): an aggressor
# tenant floods sheddable reads and saturates its device-time quota on an
# expensive chain while a victim tenant's rounds must keep flowing.
# ---------------------------------------------------------------------------


@dataclass
class NoisyNeighborResult:
    victim_rounds: int
    victim_rounds_baseline: int       # same seed, aggressor absent
    victim_reads_served: int
    victim_partials_p99: float
    period: float
    aggro_reads_served: int
    aggro_reads_shed: int
    aggro_quota_peak: float           # max quota level the aggressor hit
    aggro_quota_sheds: int            # tenant-labelled over-quota sheds
    sheds_well_formed: bool           # every shed: reason + retry + tenant
    silent_drops: int                 # sheds that carried NO tenant label
    placement: Dict[str, int] = field(default_factory=dict)
    device_seconds: Dict[str, float] = field(default_factory=dict)

    @property
    def throughput_ratio(self) -> float:
        return (self.victim_rounds
                / max(1, self.victim_rounds_baseline))

    @property
    def ok(self) -> bool:
        return (self.victim_partials_p99 < self.period
                and self.throughput_ratio >= 0.8
                and self.victim_reads_served > 0
                and self.aggro_reads_shed > 0
                and self.aggro_quota_peak >= 1.0
                and self.aggro_quota_sheds > 0
                and self.sheds_well_formed
                and self.silent_drops == 0
                and len(set(self.placement.values())) >= 2)


class NoisyNeighborScenario:
    """Two tenants on ONE daemon stack (registry + admission controller +
    verify service on an injected clock, zero network I/O):

      * ``victim`` — a cheap chain on a modest period; every fake second
        it admits one critical partial, verifies its round batch through
        the service's LIVE lane, and serves one read.  A round counts
        only if all three succeed inside the period.
      * ``aggro``  — an EXPENSIVE chain (each dispatch burns ~40x the
        victim's device time — the G2-vs-G1 cost asymmetry the flat
        per-class budget cannot see) plus a seeded flood of sheddable
        reads, with a small device-time budget and a read-rate bucket.

    The scenario runs the identical seeded timeline twice — with and
    without the aggressor — and compares the victim's per-round
    throughput (acceptance: within 20%) and critical-partials admission
    p99 (under the period).  Enforcement must be visible on the aggressor
    (rate/quota sheds with the tenant label, quota level >= 1) and
    invisible to the victim; every rejection must be well-formed (the
    Shed the transports map to 429 / RESOURCE_EXHAUSTED), never a silent
    drop; and placement must keep the two tenants' chains on different
    device groups."""

    def __init__(self, seed: int, seconds: int = 45, period: float = 10.0,
                 flood_rate: int = 20):
        self.seed = seed
        self.seconds = seconds
        self.period = period
        self.flood_rate = flood_rate

    # backend device costs (fake seconds per dispatch)
    VICTIM_COST = 0.005
    AGGRO_COST = 0.2

    def _run_timeline(self, with_aggressor: bool):
        import types as _types

        from drand_tpu.core.tenancy import TenantConfig, TenantRegistry
        from drand_tpu.crypto.device_pool import DevicePool
        from drand_tpu.crypto.verify_service import (LANE_BACKGROUND,
                                                     LANE_LIVE,
                                                     VerifyService)
        from drand_tpu.net.admission import (AdmissionController,
                                             CLASS_CRITICAL,
                                             CLASS_SHEDDABLE, Shed)

        clock = AutoClock(start=2_000.0)
        rng = random.Random(stable_seed(self.seed, "noisy-neighbor",
                                        with_aggressor))
        registry = TenantRegistry(clock=clock, device_window=10.0)
        registry.set_tenant(TenantConfig(
            name="victim", weight=2.0, device_budget=1.0,
            chains=("victim-chain",), anti_affinity=True))
        registry.set_tenant(TenantConfig(
            name="aggro", weight=1.0, rate=4.0, burst=8,
            device_budget=0.05, chains=("aggro-chain",)))
        vpk, apk = b"\x01" * 48, b"\x02" * 48
        registry.register_chain("victim-chain", pk=vpk)
        registry.register_chain("aggro-chain", pk=apk)

        class _Dev:
            pass

        pool = DevicePool(devices=[_Dev() for _ in range(2)])
        ctrl = AdmissionController(
            clock=clock, capacity=16, critical_reserve=4,
            shed_wait=0.5, recover_wait=0.05, dwell=4.0, tenancy=registry)
        svc = VerifyService(clock=clock, pad=8, background_window=0.0,
                            pool=pool)
        svc.set_tenancy(registry)

        def backend(cost):
            class _B:
                kind = "stub"

                def verify_batch(self, rounds, sigs, prev_sigs=None):
                    clock.jump(cost)        # the measured device interval
                    return np.ones(len(rounds), dtype=bool)
            return _B()

        scheme = _types.SimpleNamespace(id="noisy-stub")
        state = {"v_rounds": 0, "v_reads": 0, "a_served": 0, "a_shed": 0,
                 "a_quota_sheds": 0, "malformed": 0, "silent": 0,
                 "quota_peak": 0.0}
        holds: List[tuple] = []

        def well_formed(s: Shed, expect_tenant: Optional[str]) -> bool:
            if s.retry_after <= 0 or not s.reason:
                return False
            if expect_tenant is not None and s.tenant != expect_tenant:
                return False
            return True

        try:
            h_victim = svc.handle(scheme, vpk, backend=backend(
                self.VICTIM_COST))
            h_aggro = svc.handle(scheme, apk, backend=backend(
                self.AGGRO_COST))
            placement = {"victim": h_victim.gid, "aggro": h_aggro.gid}
            for sec in range(self.seconds):
                now = clock.monotonic()
                holds[:] = [(at, t) for at, t in holds
                            if at > now or (t.release() and False)]
                if with_aggressor:
                    # the flood: seeded burst of sheddable reads, some
                    # held for a few fake seconds to pressure the pool
                    for i in range(rng.randrange(self.flood_rate // 2,
                                                 self.flood_rate * 2)):
                        ticket, s = ctrl.try_admit(CLASS_SHEDDABLE,
                                                   peer=f"edge{i % 4}",
                                                   tenant="aggro")
                        if ticket is not None:
                            state["a_served"] += 1
                            holds.append((now + rng.uniform(1.0, 3.0),
                                          ticket))
                        else:
                            state["a_shed"] += 1
                            if s.tenant is None:
                                state["silent"] += 1
                            if not well_formed(s, None):
                                state["malformed"] += 1
                            if s.reason in ("tenant-level",
                                            "tenant-rate",
                                            "tenant-share"):
                                state["a_quota_sheds"] += 1
                    # the expensive chain: one background batch per
                    # second, burning ~4x the aggressor's device budget
                    h_aggro.verify_batch(list(range(sec * 8, sec * 8 + 8)),
                                         [b"a"] * 8,
                                         lane=LANE_BACKGROUND)
                    state["quota_peak"] = max(state["quota_peak"],
                                              registry.quota_level("aggro"))
                # the victim's round: critical partial + live verify +
                # one served read, all inside the period
                t0 = clock.monotonic()
                pt = ctrl.admit(CLASS_CRITICAL, peer="signer",
                                tenant="victim")
                pt.release()
                verdict = h_victim.verify_batch(
                    list(range(sec * 4, sec * 4 + 4)), [b"v"] * 4,
                    lane=LANE_LIVE)
                read, s = ctrl.try_admit(CLASS_SHEDDABLE, peer="vclient",
                                         tenant="victim")
                if read is not None:
                    state["v_reads"] += 1
                    read.release()
                elif s is not None and not well_formed(s, None):
                    state["malformed"] += 1
                if verdict.all() and read is not None \
                        and clock.monotonic() - t0 <= self.period:
                    state["v_rounds"] += 1
                clock.jump(1.0)
            partials_p99 = ctrl.wait_p99(CLASS_CRITICAL)
            device = {t: round(registry.device_seconds_total(t), 3)
                      for t in ("victim", "aggro")}
        finally:
            for _, t in holds:
                t.release()
            svc.stop()
        return state, partials_p99, placement, device

    def run(self) -> NoisyNeighborResult:
        base, _, _, _ = self._run_timeline(with_aggressor=False)
        loud, p99, placement, device = self._run_timeline(
            with_aggressor=True)
        return NoisyNeighborResult(
            victim_rounds=loud["v_rounds"],
            victim_rounds_baseline=base["v_rounds"],
            victim_reads_served=loud["v_reads"],
            victim_partials_p99=p99,
            period=self.period,
            aggro_reads_served=loud["a_served"],
            aggro_reads_shed=loud["a_shed"],
            aggro_quota_peak=loud["quota_peak"],
            aggro_quota_sheds=loud["a_quota_sheds"],
            sheds_well_formed=loud["malformed"] == 0
            and loud["a_shed"] > 0,
            silent_drops=loud["silent"],
            placement=placement,
            device_seconds=device)


# ---------------------------------------------------------------------------
# stolen-identity scenario (ISSUE 19): the authenticated identity plane
# under active identity theft — real daemons, real mTLS gRPC on localhost
# ---------------------------------------------------------------------------

@dataclass
class StolenIdentityResult:
    """Verdict of one stolen-identity run against a live mTLS fleet."""
    plaintext_rejected: bool          # no-cert client cannot even connect
    victim_index: int
    forged_packets: int               # forged sender_index packets sent
    impersonation_rejected: int       # ... of which INVALID_ARGUMENT'd
    impersonation_metered: bool       # identity_rejections{handel} moved
    liveness_after_forgery: bool      # chain advanced past the flood
    good_token_served: bool
    token_reasons: Dict[str, str] = field(default_factory=dict)
    token_trailers: Dict[str, str] = field(default_factory=dict)
    victim_quota_untouched: bool = False
    rekey_over_rotation: bool = False  # second DKG with certs rotating
    rotation_epochs: List[int] = field(default_factory=list)
    liveness_after_rotation: bool = False
    control_plaintext_ok: bool = False  # no-identity fleet serves plain
    control_header_ignored: bool = False   # token header: same bytes
    digest: str = ""

    @property
    def ok(self) -> bool:
        reasons_ok = (self.token_reasons.get("revoked") == "revoked"
                      and self.token_reasons.get("expired") == "expired"
                      and self.token_reasons.get("tampered")
                      == "bad-signature")
        trailers_ok = all(self.token_trailers.get(k) == v for k, v in
                          self.token_reasons.items())
        return (self.plaintext_rejected
                and self.impersonation_rejected == self.forged_packets > 0
                and self.impersonation_metered
                and self.liveness_after_forgery
                and self.good_token_served
                and reasons_ok and trailers_ok
                and self.victim_quota_untouched
                and self.rekey_over_rotation
                and all(e >= 1 for e in self.rotation_epochs)
                and self.liveness_after_rotation
                and self.control_plaintext_ok
                and self.control_header_ignored)


class StolenIdentityScenario:
    """Identity theft against a live 3-node mTLS committee
    (net/identity.py + core/authz.py; ISSUE 19).

    The fleet runs real `DrandDaemon`s over localhost gRPC with per-node
    certs from one private CA.  The attacker holds a VALID CA-signed
    cert — transport authentication alone would admit it — whose SAN set
    (`attacker.example` only) covers no roster entry.  Legs:

      * **Forged sender_index over mTLS**: the attacker sends Handel
        candidates claiming a victim's group index.  Every packet must
        be rejected at ingress (INVALID_ARGUMENT naming the
        authenticated identity), metered under
        `identity_rejections{surface="handel"}`, and the chain must
        keep producing — the victim is never demoted by the forgery.
      * **Stolen/replayed tokens**: a revoked token replayed, an expired
        token, and a tampered token are each refused UNAUTHENTICATED
        with an `identity-reason` trailer BEFORE any quota spend — no
        metric series ever attributes the attempts to the victim
        tenant.  The genuine token keeps being served.
      * **Cert rotation mid-rekey**: every node's cert is rotated while
        a second-chain DKG is in flight; the exchange completes, every
        plane hot-reloads (epoch bump) without a restart, and rounds
        keep flowing.
      * **No-identity control run**: a fleet without `identity_dir`
        serves plaintext exactly as before — a bearer header on an
        untenanted daemon changes nothing, byte for byte.

    Real daemons produce rounds on wall clocks, so `digest` covers the
    seed-stable verdict surface (reasons, counts, flags), not beacon
    bytes."""

    def __init__(self, seed: int, root: str, period: int = 4):
        self.seed = seed
        self.root = root
        self.period = period
        dice = random.Random(stable_seed(seed, "stolen-identity"))
        self.victim_node = dice.randrange(1, 3)   # never the DKG leader

    # -- helpers -------------------------------------------------------------

    def _mk_daemon(self, folder, identity_dir=None):
        from drand_tpu.core.config import Config
        from drand_tpu.core.daemon import DrandDaemon
        cfg = Config(folder=folder, control_port=0,
                     private_listen="127.0.0.1:0", dkg_timeout=2,
                     dkg_kickoff_grace=0.8, use_device_verifier=False,
                     db_engine="memdb", handel_min_group=2,
                     identity_dir=identity_dir,
                     identity_reload_interval=0.5)
        d = DrandDaemon(cfg)
        d.start()
        return d

    def _run_dkg(self, daemons, sup_dir, beacon_id="default"):
        import time

        from drand_tpu.net import ControlClient, convert
        from drand_tpu.protos import drand_pb2 as pb
        leader_addr = daemons[0].gateway.listen_addr
        results = [None] * len(daemons)
        errors = []

        def drive(i):
            cc = ControlClient(daemons[i].control.port,
                               identity_dir=sup_dir)
            req = pb.InitDKGPacket(
                info=pb.SetupInfo(
                    leader=(i == 0),
                    leader_address="" if i == 0 else leader_addr,
                    nodes=len(daemons), threshold=2,
                    timeout_seconds=30, secret=b"stolen-id"),
                beacon_period_seconds=self.period,
                metadata=convert.metadata(beacon_id))
            deadline = time.monotonic() + 30
            while True:
                try:
                    results[i] = cc.stub.init_dkg(req, timeout=120)
                    return
                except Exception as e:
                    if i == 0 or time.monotonic() >= deadline:
                        errors.append((i, e))
                        return
                    time.sleep(0.2)

        ts = [threading.Thread(target=drive, args=(i,),
                               name=f"stolen-dkg-{i}")
              for i in range(len(daemons))]
        for t in ts:
            t.start()
        return ts, results, errors

    def _wait_round(self, pc, addr, round_, timeout=90, beacon_id="default"):
        import time

        from drand_tpu.net import Peer
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                r = pc.public_rand(Peer(addr), 0, beacon_id)
                if r.round >= round_:
                    return r
            except Exception:
                pass
            time.sleep(0.4)
        raise AssertionError(f"round {round_} not reached on {addr}")

    @staticmethod
    def _victim_tenant_lines():
        from drand_tpu.metrics import scrape
        return sorted(
            l for l in scrape("private").decode().splitlines()
            if 'tenant="victim"' in l and not l.startswith("#"))

    # -- the run -------------------------------------------------------------

    def run(self) -> StolenIdentityResult:
        import time

        import grpc

        from drand_tpu.beacon import handel as H
        from drand_tpu.metrics import identity_rejections
        from drand_tpu.net import convert, services
        from drand_tpu.net.identity import (IdentityPlane, issue_cert,
                                            provision_fleet)
        from drand_tpu.net import ControlClient, Peer, ProtocolClient
        from drand_tpu.protos import drand_pb2 as pb

        id_root = os.path.join(self.root, "identity")
        certs = provision_fleet(
            id_root, {f"n{i}": ["127.0.0.1"] for i in range(3)}
            | {"supervisor": ["127.0.0.1"]}, days=365)
        ca_dir = os.path.join(id_root, "ca")
        # the attacker's cert IS CA-signed — transport auth admits it —
        # but its SAN set covers no roster host
        attacker_dir = issue_cert(os.path.join(id_root, "attacker"),
                                  "attacker", ["attacker.example"], ca_dir)
        sup_dir = certs["supervisor"]

        daemons = [self._mk_daemon(os.path.join(self.root, f"n{i}"),
                                   identity_dir=certs[f"n{i}"])
                   for i in range(3)]
        control_daemons = []
        result = StolenIdentityResult(
            plaintext_rejected=False, victim_index=-1, forged_packets=0,
            impersonation_rejected=0, impersonation_metered=False,
            liveness_after_forgery=False, good_token_served=False)
        try:
            addr0 = daemons[0].gateway.listen_addr

            # -- leg 0: a certless client cannot reach the plane at all
            with grpc.insecure_channel(addr0) as chan:
                try:
                    services.PUBLIC.stub(chan).public_rand(
                        pb.PublicRandRequest(
                            metadata=convert.metadata("default")),
                        timeout=5)
                except grpc.RpcError:
                    result.plaintext_rejected = True

            ts, dkg_results, errors = self._run_dkg(daemons, sup_dir)
            for t in ts:
                t.join(timeout=150)
            assert not errors, errors
            group = convert.proto_to_group(dkg_results[0])

            pc = ProtocolClient(identity=IdentityPlane(sup_dir))
            head = self._wait_round(pc, addr0, 1).round

            # -- leg A: forged sender_index through an authenticated
            # channel.  The claimed index belongs to a DIFFERENT node.
            victim_addr = daemons[self.victim_node].gateway.listen_addr
            victim_idx = next(n.index for n in group.nodes
                              if n.identity.addr == victim_addr)
            result.victim_index = victim_idx
            metered0 = identity_rejections.labels(
                "handel", "impersonation")._value.get()
            atk_chan = grpc.secure_channel(
                addr0, IdentityPlane(attacker_dir).channel_credentials(),
                options=(("grpc.ssl_target_name_override", "localhost"),))
            atk = services.PROTOCOL.stub(atk_chan)
            forged = H.to_packet(
                head, b"", 1, victim_idx,
                H.Aggregate({victim_idx: victim_idx.to_bytes(2, "big")
                             + b"\x5a" * 48}), len(group), "default")
            for _ in range(4):
                result.forged_packets += 1
                try:
                    atk.handel_aggregate(forged, timeout=10)
                except grpc.RpcError as e:
                    if (e.code() == grpc.StatusCode.INVALID_ARGUMENT
                            and "authenticated as attacker"
                            in (e.details() or "")):
                        result.impersonation_rejected += 1
            atk_chan.close()
            metered1 = identity_rejections.labels(
                "handel", "impersonation")._value.get()
            result.impersonation_metered = \
                metered1 - metered0 >= result.forged_packets
            # the victim was never demoted: every node keeps producing
            for d in daemons:
                self._wait_round(pc, d.gateway.listen_addr, head + 2,
                                 timeout=20 * self.period)
            result.liveness_after_forgery = True

            # -- leg B: stolen tokens.  All rejections land BEFORE any
            # quota spend attributable to the victim tenant.
            cc0 = ControlClient(daemons[0].control.port,
                                identity_dir=sup_dir)
            quota_before = self._victim_tenant_lines()

            def present(token, round_=0):
                """public_rand with a bearer token; returns
                (response|None, reason-trailer|None)."""
                chan = grpc.secure_channel(
                    addr0, IdentityPlane(sup_dir).channel_credentials(),
                    options=(("grpc.ssl_target_name_override",
                              "localhost"),))
                try:
                    resp = services.PUBLIC.stub(chan).public_rand(
                        pb.PublicRandRequest(
                            round=round_,
                            metadata=convert.metadata("default")),
                        metadata=(("authorization", f"Bearer {token}"),),
                        timeout=10)
                    return resp, None
                except grpc.RpcError as e:
                    assert e.code() == grpc.StatusCode.UNAUTHENTICATED, e
                    reason = dict(e.trailing_metadata() or ()).get(
                        "identity-reason")
                    return None, reason
                finally:
                    chan.close()

            minted = cc0.stub.token_mint(pb.TokenMintRequest(
                tenant="victim", chains=["default"], ttl_seconds=3600,
                metadata=convert.metadata("default")), timeout=10)
            resp, _ = present(minted.token)
            result.good_token_served = resp is not None and resp.round >= 1

            # replay after revocation
            cc0.stub.token_revoke(pb.TokenRequest(
                token_id=minted.token_id,
                metadata=convert.metadata("default")), timeout=10)
            _, reason = present(minted.token)
            result.token_reasons["revoked"] = reason
            result.token_trailers["revoked"] = reason

            # expired (shrink the authority's skew window in-process so
            # the leg doesn't wait out the 30 s default)
            authority = daemons[0].authority
            old_skew = authority.skew
            authority.skew = 0.2
            try:
                short = cc0.stub.token_mint(pb.TokenMintRequest(
                    tenant="victim", chains=["default"],
                    ttl_seconds=0.2,
                    metadata=convert.metadata("default")), timeout=10)
                time.sleep(0.8)
                _, reason = present(short.token)
                result.token_reasons["expired"] = reason
                result.token_trailers["expired"] = reason
            finally:
                authority.skew = old_skew

            # tampered signature
            parts = minted.token.split(".")
            parts[-1] = ("0" if parts[-1][0] != "0" else "1") \
                + parts[-1][1:]
            _, reason = present(".".join(parts))
            result.token_reasons["tampered"] = reason
            result.token_trailers["tampered"] = reason

            result.victim_quota_untouched = \
                self._victim_tenant_lines() == quota_before

            # -- leg C: rotate every node's cert while a second-chain
            # DKG (a full protocol-plane key exchange) is in flight
            ts, rot_results, rot_errors = self._run_dkg(
                daemons, sup_dir, beacon_id="rot")
            time.sleep(0.6)
            for i in range(3):
                issue_cert(certs[f"n{i}"], f"n{i}",
                           ["127.0.0.1", "localhost"], ca_dir)
            for t in ts:
                t.join(timeout=150)
            result.rekey_over_rotation = (not rot_errors
                                          and all(r is not None
                                                  for r in rot_results))
            # every plane converges on the rotated trio without restart
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                result.rotation_epochs = [d.identity.epoch
                                          for d in daemons]
                if all(e >= 1 for e in result.rotation_epochs):
                    break
                for d in daemons:
                    d.identity.maybe_reload()
                time.sleep(0.3)
            head2 = self._wait_round(pc, addr0, 1, timeout=60,
                                     beacon_id="rot").round
            self._wait_round(pc, addr0, head2 + 1,
                             timeout=20 * self.period, beacon_id="rot")
            result.liveness_after_rotation = True

            # -- control run: no identity_dir => plaintext plane, and a
            # bearer header on an untenanted daemon changes NOTHING
            control_daemons = [
                self._mk_daemon(os.path.join(self.root, f"c{i}"))
                for i in range(2)]
            ts, c_results, c_errors = self._run_dkg2(control_daemons)
            for t in ts:
                t.join(timeout=150)
            assert not c_errors, c_errors
            c_addr = control_daemons[0].gateway.listen_addr
            plain_pc = ProtocolClient()
            self._wait_round(plain_pc, c_addr, 1)
            with grpc.insecure_channel(c_addr) as chan:
                stub = services.PUBLIC.stub(chan)
                req = pb.PublicRandRequest(
                    round=1, metadata=convert.metadata("default"))
                bare = stub.public_rand(req, timeout=10)
                tokened = stub.public_rand(
                    req, metadata=(("authorization", "Bearer dt1.junk"),),
                    timeout=10)
            result.control_plaintext_ok = bare.round == 1
            result.control_header_ignored = (
                bare.SerializeToString() == tokened.SerializeToString()
                and control_daemons[0].identity is None
                and not control_daemons[0].authority.active())

            ident = repr((self.seed, self.victim_node,
                          result.forged_packets,
                          result.impersonation_rejected,
                          sorted(result.token_reasons.items()),
                          result.victim_quota_untouched,
                          result.rekey_over_rotation))
            result.digest = hashlib.sha256(
                ident.encode()).hexdigest()[:16]
            return result
        finally:
            for d in daemons + control_daemons:
                d.stop()

    def _run_dkg2(self, daemons):
        """2-node variant for the control fleet (threshold 2 of 2)."""
        import time

        from drand_tpu.net import ControlClient, convert
        from drand_tpu.protos import drand_pb2 as pb
        leader_addr = daemons[0].gateway.listen_addr
        results = [None] * len(daemons)
        errors = []

        def drive(i):
            cc = ControlClient(daemons[i].control.port)
            req = pb.InitDKGPacket(
                info=pb.SetupInfo(
                    leader=(i == 0),
                    leader_address="" if i == 0 else leader_addr,
                    nodes=len(daemons), threshold=2,
                    timeout_seconds=30, secret=b"stolen-id"),
                beacon_period_seconds=self.period,
                metadata=convert.metadata("default"))
            deadline = time.monotonic() + 30
            while True:
                try:
                    results[i] = cc.stub.init_dkg(req, timeout=120)
                    return
                except Exception as e:
                    if i == 0 or time.monotonic() >= deadline:
                        errors.append((i, e))
                        return
                    time.sleep(0.2)

        ts = [threading.Thread(target=drive, args=(i,),
                               name=f"stolen-control-dkg-{i}")
              for i in range(len(daemons))]
        for t in ts:
            t.start()
        return ts, results, errors
