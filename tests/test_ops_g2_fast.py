"""Round-4 G2 hot-path machinery vs the host golden code (VERDICT r3 #3):

  * single-scan sqrt_ratio front end (q = p² = 9 mod 16, eta candidates)
    behind map_to_g2_jac / g2_recover_y / the fused g2_decompress_and_hash
  * psi² endomorphism identity (the G2 GLV eigenvalue x²)
  * the psi-split joint ladder g2_glv_msm_terms vs a plain 256-bit ladder
  * tower.fp2_pow_fixed vs host fp2_pow

Host code is pinned by LoE mainnet vectors (tests/test_host_crypto.py),
so agreement here anchors the new G2 kernels to real beacon data.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np

from drand_tpu.crypto.host import curve as C
from drand_tpu.crypto.host import field as HF
from drand_tpu.crypto.host import h2c as HH
from drand_tpu.crypto.host import serialize as S
from drand_tpu.crypto.host.params import DST_G2, P, R, X as BLS_X
from drand_tpu.ops import curve as DC
from drand_tpu.ops import h2c as DH
from drand_tpu.ops import tower as T

random.seed(41)


def test_fp2_pow_fixed_matches_host():
    xs = [(random.randrange(P), random.randrange(P)) for _ in range(4)]
    e = (P * P - 9) // 16
    enc = T.encode_fp2
    a = (jnp.stack([enc(x)[0] for x in xs]), jnp.stack([enc(x)[1] for x in xs]))
    out = jax.jit(lambda a: T.fp2_pow_fixed(a, e))(a)
    got = [T.decode_fp2((out[0][i], out[1][i])) for i in range(4)]
    assert got == [HF.fp2_pow(x, e) for x in xs]


def test_map_and_recover_and_fused_match_host():
    msgs = [b"g2fast-%d" % i for i in range(4)]
    u0, u1 = DH.hash_msgs_to_field_g2(msgs, DST_G2)
    pts = jax.jit(DH.hash_to_g2_jac)(u0, u1)
    got = DC.decode_g2_points(pts)
    assert got == [HH.hash_to_curve_g2(m, DST_G2) for m in msgs]

    # decompression round-trip through the candidate-select sqrt
    from drand_tpu.crypto.batch import _wire_parse
    wire = [S.g2_to_bytes(p) for p in got]
    xw, sign, bad = _wire_parse(wire, True)
    assert not bad.any()
    x0 = jnp.asarray(np.ascontiguousarray(xw[:, 0]))
    x1 = jnp.asarray(np.ascontiguousarray(xw[:, 1]))
    pt, ok = jax.jit(DH.g2_recover_y)(x0, x1, jnp.asarray(sign))
    assert np.asarray(ok).all()
    assert DC.decode_g2_points(pt) == got

    # fused 3N-wide scan == the two parts
    sig_jac, ok2, hm = jax.jit(DH.g2_decompress_and_hash)(
        x0, x1, jnp.asarray(sign), u0, u1)
    assert np.asarray(ok2).all()
    assert DC.decode_g2_points(sig_jac) == got
    assert DC.decode_g2_points(hm) == got


def test_psi2_eigenvalue_and_glv_ladder():
    ks = [random.randrange(1, R) for _ in range(2)]
    pts = [C.G2.mul(C.G2.gen, k) for k in ks]
    q = DC.encode_g2_points(pts)

    # psi²(Q) == [x²]Q on G2
    lhs = DC.decode_g2_points(jax.jit(DC.g2_psi2)(q))
    rhs = DC.decode_g2_points(jax.jit(
        lambda p: DC.G2_DEV.scalar_mul_fixed(p, BLS_X ** 2))(q))
    assert lhs == rhs

    # joint (k0 + x²k1) ladder == plain 256-bit ladder on the same scalar
    k0 = [random.randrange(2 ** 32) for _ in range(2)]
    k1 = [random.randrange(2 ** 32) for _ in range(2)]
    b0 = DC.scalars_to_bits(k0, nbits=32)
    b1 = DC.scalars_to_bits(k1, nbits=32)
    got = DC.decode_g2_points(jax.jit(DC.g2_glv_msm_terms)(q, b0, b1))
    full = [k0[i] + BLS_X ** 2 * k1[i] for i in range(2)]
    ref = DC.decode_g2_points(jax.jit(DC.G2_DEV.scalar_mul_bits)(
        q, DC.scalars_to_bits(full, nbits=256)))
    assert got == ref
    # host cross-check on the composed scalar
    assert got == [C.G2.mul(pts[i], full[i] % R) for i in range(2)]
