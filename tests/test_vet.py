"""tpu-vet (drand_tpu/analysis): the tier-1 gate + the fixture corpus.

Two jobs:
  1. `test_package_is_vet_clean` gates the repo: the whole drand_tpu
     package must vet clean (zero unsuppressed findings) — the
     static-analysis analogue of `go vet` in the reference's CI.
  2. Every checker is proven against tests/lint_fixtures/: each seeded
     violation is caught, each negative case stays silent, and the
     suppression + baseline machinery actually suppresses/baselines.

The analyzer parses target files without importing them, so none of
this touches JAX (the subprocess test pins that).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from drand_tpu.analysis import load_baseline, run_vet, write_baseline
from drand_tpu.analysis.checkers import (ALL_CHECKERS, by_names,
                                         checker_names)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "drand_tpu")
TOOLS = os.path.join(REPO, "tools")
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")

pytestmark = pytest.mark.vet


def _codes(report, path=None):
    return {(f.path, f.code) for f in report.findings
            if path is None or f.path == path}


def _fixture_report(checker_name):
    return run_vet([FIXTURES], checkers=by_names([checker_name]))


# -- the tier-1 gate ----------------------------------------------------------


def test_package_is_vet_clean():
    """Package + operator tools vet clean, fast, with all 13 checkers
    (the new recompile/deadline/threadlife/metriclabel gates included)."""
    t0 = time.perf_counter()
    report = run_vet([PACKAGE, TOOLS])
    elapsed = time.perf_counter() - t0
    assert report.errors == []
    assert report.findings == [], (
        "unsuppressed tpu-vet findings:\n"
        + "\n".join(f.render() for f in report.findings))
    assert report.files > 80            # the whole package was really walked
    assert elapsed < 30                 # seconds, generous for a loaded box


def test_cli_runs_clean_without_importing_jax():
    """`tools/vet.py drand_tpu/` exits 0 and never imports JAX — the
    acceptance criterion, checked in a fresh interpreter."""
    probe = (
        "import sys\n"
        "sys.argv = ['vet', %r, %r]\n"
        "sys.path.insert(0, %r)\n"
        "import runpy\n"
        "try:\n"
        "    runpy.run_path(%r, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, f'vet exit {e.code}'\n"
        "leaked = [m for m in sys.modules\n"
        "          if m == 'jax' or m.startswith('jax.')]\n"
        "assert not leaked, f'vet imported JAX: {leaked}'\n"
    ) % (PACKAGE, TOOLS, REPO, os.path.join(REPO, "tools", "vet.py"))
    proc = subprocess.run([sys.executable, "-c", probe],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- per-checker fixture proofs ----------------------------------------------


def test_clock_checker_catches_fixture():
    report = _fixture_report("clock")
    codes = _codes(report, "clock_bad.py")
    assert ("clock_bad.py", "clock-direct-call") in codes
    lines = {f.line for f in report.findings if f.path == "clock_bad.py"}
    # direct, aliased, and both from-imports are caught
    assert len(lines) == 4, sorted(lines)
    # perf_counter and the two suppressed calls are NOT findings
    texts = "\n".join(f.message for f in report.findings)
    assert "perf_counter" not in texts
    assert len([f for f in report.suppressed
                if f.path == "clock_bad.py"]) == 2


def test_lock_checker_catches_fixture():
    report = _fixture_report("lock")
    codes = _codes(report)
    assert ("locks_bad.py", "lock-unguarded-write") in codes
    assert ("locks_bad.py", "lock-blocking-call") in codes
    assert ("locks_bad.py", "lock-order-cycle") in codes
    msgs = [f.message for f in report.findings]
    # the two seeded unguarded writes in reset(), not the locked one
    assert sum("UnguardedWrite.reset " in m for m in msgs) == 2
    # blocking: Queue.get and Event.wait; never get_nowait/block=False/cv
    assert any("Queue.get" in m for m in msgs)
    assert any("Event.wait" in m for m in msgs)
    assert not any("fast_path" in m or "nonblocking" in m or "cv_wait" in m
                   for m in msgs)
    # cycle: OrderAB both ways + the SelfDeadlock re-entry; RLock is fine
    cycles = [m for m in msgs if "cycle" in m]
    assert any("OrderAB" in m for m in cycles)
    assert any("SelfDeadlock" in m for m in cycles)
    assert not any("ReentrantOk" in m for m in cycles)
    assert len([f for f in report.suppressed
                if f.path == "locks_bad.py"]) == 1


def test_secret_checker_catches_fixture():
    report = _fixture_report("secret")
    codes = _codes(report, "secrets_bad.py")
    assert ("secrets_bad.py", "secret-in-log") in codes
    assert ("secrets_bad.py", "secret-in-exception") in codes
    assert ("secrets_bad.py", "secret-in-repr") in codes
    msgs = [f.message for f in report.findings
            if f.path == "secrets_bad.py"]
    # direct kwarg + one-hop taint are both caught
    assert sum("secret-bearing" in m and "log call" in m
               for m in msgs) == 2
    # hash_secret() sanitizes; literals are not values
    assert not any("proof" in m for m in msgs)
    assert len([f for f in report.suppressed
                if f.path == "secrets_bad.py"]) == 1


def test_secret_checker_covers_identity_plane_material():
    """Token root keys and TLS private keys (the PR 19 identity plane)
    are secret material: log kwargs, print, exception messages and
    __repr__ all flag; token ids, public cert PEMs and len()/
    hash_secret()-sanitized values stay silent."""
    report = _fixture_report("secret")
    path = "net/identity_bad.py"
    codes = _codes(report, path)
    assert (path, "secret-in-log") in codes
    assert (path, "secret-in-exception") in codes
    assert (path, "secret-in-repr") in codes
    msgs = [f.message for f in report.findings if f.path == path]
    assert any("_root_key" in m for m in msgs)
    assert any("key_pem" in m for m in msgs)
    # the five seeded leaks, nothing else: the public halves
    # (token_id, cert_pem) and the sanitizers never flag
    assert len(msgs) == 5, msgs
    assert not any("token_id" in m or "cert_pem" in m for m in msgs)
    assert len([f for f in report.suppressed if f.path == path]) == 1


def test_trace_checker_catches_fixture():
    report = _fixture_report("trace")
    codes = _codes(report, "ops/trace_bad.py")
    assert ("ops/trace_bad.py", "trace-python-branch") in codes
    assert ("ops/trace_bad.py", "trace-python-loop") in codes
    assert ("ops/trace_bad.py", "trace-concretize") in codes
    assert ("ops/trace_bad.py", "trace-captured-mutation") in codes
    msgs = [f.message for f in report.findings]
    # negatives: static args, shape-derived values, host-side functions
    assert not any("static_is_fine" in m for m in msgs)
    assert not any("shapes_are_static" in m for m in msgs)
    assert not any("host_side" in m for m in msgs)
    assert len([f for f in report.suppressed
                if f.path == "ops/trace_bad.py"]) == 1


def test_trace_sync_in_loop_catches_fixture():
    """ISSUE 10 satellite: synchronous device readback inside a per-chunk
    loop in crypto/ hot paths — the exact class the depth-k pipelined
    executor exists to remove."""
    report = _fixture_report("trace")
    sync = [f for f in report.findings
            if f.path == "crypto/sync_bad.py"]
    assert sync and all(f.code == "trace-sync-in-loop" for f in sync)
    # bool / np.asarray / jax.block_until_ready in the for loop, float /
    # .block_until_ready in the while loop, and the nested host loop —
    # all six seeded, each exactly ONCE (no double report through the
    # enclosing function)
    assert len(sync) == len({f.line for f in sync}) == 6, \
        sorted(f.line for f in sync)
    msgs = [f.message for f in sync]
    assert any("bool()" in m for m in msgs)
    assert any("asarray()" in m for m in msgs)
    assert any(".block_until_ready()" in m for m in msgs)
    assert any("float()" in m for m in msgs)
    # the nested host loop is attributed to the INNER function
    assert any("inner()" in m for m in msgs)
    # negatives: sync after the stream, host numpy in a loop, and a loop
    # inside a nested JITTED function (traced device code)
    assert not any("sync_once_after_stream" in m for m in msgs)
    assert not any("host_work_in_loop" in m for m in msgs)
    assert not any("jitted_inner" in m or "run()" in m for m in msgs)
    # the justified per-chunk bisection readback is a suppression
    assert len([f for f in report.suppressed
                if f.path == "crypto/sync_bad.py"]) == 1


def test_trace_host_hash_in_loop_catches_fixture():
    """ISSUE 14 satellite: per-lane host hashing inside loops on the
    hot-path modules — the exact stage device hash-to-field removed
    from the steady-state pack path."""
    report = _fixture_report("trace")
    hits = [f for f in report.findings
            if f.path == "ops/hash_bad.py"
            and f.code == "trace-host-hash-in-loop"]
    # direct hashlib in a for loop, the aliased `sha256` in a while
    # loop, the h2f helper comprehension, and the digest_beacon
    # comprehension — four seeded, each exactly once
    assert len(hits) == len({f.line for f in hits}) == 4, \
        sorted(f.line for f in hits)
    msgs = [f.message for f in hits]
    assert any("hashlib.sha256" in m for m in msgs)
    assert any("hash_to_field_fp()" in m for m in msgs)
    assert any("digest_beacon()" in m for m in msgs)
    # negatives: one digest outside the loop, numpy packing per message
    assert not any("hash_once_outside_loop" in m for m in msgs)
    assert not any("numpy_pack_loop" in m for m in msgs)
    # the justified parity-oracle site is a suppression, not a finding
    assert not any("justified_oracle" in m for m in msgs)
    assert any(f.path == "ops/hash_bad.py" and
               f.code == "trace-host-hash-in-loop"
               for f in report.suppressed)


def test_store_checker_catches_fixture():
    report = _fixture_report("store")
    codes = _codes(report, "store_bad.py")
    assert ("store_bad.py", "store-missing-durability") in codes
    assert ("store_bad.py", "store-conn-unlocked") in codes
    assert ("store_bad.py", "store-put-no-commit") in codes
    msgs = [f.message for f in report.findings]
    assert any("NoDurabilityStore" in m for m in msgs)
    assert not any("DeclaredStore" in m for m in msgs)
    # locked accesses and the committing delete are not flagged
    assert not any(".last " in m for m in msgs)
    assert sum("ForeignConnCursor" in m for m in msgs) == 1


def test_verifier_checker_catches_fixture():
    report = _fixture_report("verifier")
    codes = _codes(report, "verifier_bad.py")
    assert ("verifier_bad.py", "verifier-direct-construction") in codes
    assert ("verifier_bad.py", "verifier-device-enumeration") in codes
    lines = {f.line for f in report.findings
             if f.path == "verifier_bad.py"}
    # direct, module-attr and aliased constructions + the three raw
    # device enumerations (jax.devices/local_devices/from-import alias)
    # are all caught
    assert len(lines) == 6, sorted(lines)
    msgs = "\n".join(f.message for f in report.findings)
    # the service route, the host fallback and the pool route are NOT
    # flagged
    assert "get_service" not in msgs
    assert "HostBatchVerifier" not in msgs
    assert len([f for f in report.suppressed
                if f.path == "verifier_bad.py"]) == 2
    # crypto/-prefixed modules own the pipelines: construction exempt
    assert not any(f.path.startswith("crypto/")
                   and f.code == "verifier-direct-construction"
                   for f in report.findings)
    # ... but device ENUMERATION is only sanctioned in the pool module
    # itself: a crypto/ sibling is flagged, crypto/device_pool.py is not
    assert ("crypto/pool_bad.py", "verifier-device-enumeration") \
        in _codes(report)
    assert not any(f.path == "crypto/device_pool.py"
                   for f in report.findings)


def test_wait_checker_catches_fixture():
    report = _fixture_report("wait")
    codes = _codes(report, "wait_bad.py")
    assert ("wait_bad.py", "wait-unbounded") in codes
    lines = {f.line for f in report.findings if f.path == "wait_bad.py"}
    # future.result, thread.join, condition.wait, event.wait — all caught
    assert len(lines) == 4, sorted(lines)
    msgs = [f.message for f in report.findings
            if f.path == "wait_bad.py"]
    assert any(".result()" in m for m in msgs)
    assert any(".join()" in m for m in msgs)
    assert any(".wait()" in m for m in msgs)
    # bounded variants, str.join, get_nowait stay silent; the justified
    # suppression is a suppression, not a finding
    assert len([f for f in report.suppressed
                if f.path == "wait_bad.py"]) == 1


def test_bounds_checker_catches_fixture():
    report = _fixture_report("bounds")
    codes = _codes(report, "net/bounds_bad.py")
    assert ("net/bounds_bad.py", "bounds-unbounded-queue") in codes
    assert ("net/bounds_bad.py", "bounds-unbounded-executor") in codes
    assert ("net/bounds_bad.py", "bounds-thread-per-request") in codes
    lines = {f.line for f in report.findings
             if f.path == "net/bounds_bad.py"}
    # bare Queue, from-import alias, maxsize=0, LifoQueue, SimpleQueue,
    # bare executor, ThreadingHTTPServer call + subclass — all caught
    assert len(lines) == 8, sorted(lines)
    msgs = [f.message for f in report.findings]
    # bounded constructs and the plain HTTPServer stay silent
    assert not any("max_workers=4" in m for m in msgs)
    assert not any(f.line in (21, 22, 24, 31, 32, 38)
                   for f in report.findings
                   if f.path == "net/bounds_bad.py")
    assert len([f for f in report.suppressed
                if f.path == "net/bounds_bad.py"]) == 1


def test_atomic_checker_catches_fixture():
    report = _fixture_report("atomic")
    codes = _codes(report, "key/atomic_bad.py")
    assert ("key/atomic_bad.py", "atomic-write-in-place") in codes
    lines = {f.line for f in report.findings
             if f.path == "key/atomic_bad.py"}
    # open("w"), os.open(O_CREAT|O_TRUNC), open("a") — all caught
    assert len(lines) == 3, sorted(lines)
    msgs = [f.message for f in report.findings
            if f.path == "key/atomic_bad.py"]
    # the tempfile+os.replace and fs.write_atomic routes stay silent
    assert not any("save_group_atomic" in m or "save_share_atomic" in m
                   or "load_group" in m for m in msgs)
    # the justified lockfile write is a suppression, not a finding
    assert len([f for f in report.suppressed
                if f.path == "key/atomic_bad.py"]) == 1


def test_atomic_checker_scoped_to_key_plane(tmp_path):
    """An in-place write OUTSIDE key/ + core/dkg_journal.py is not this
    checker's business (e.g. bench JSON dumps are not identity state)."""
    src = tmp_path / "bench_out.py"
    src.write_text("def dump(path, data):\n"
                   "    with open(path, 'w') as f:\n"
                   "        f.write(data)\n")
    report = run_vet([str(src)], checkers=by_names(["atomic"]))
    assert report.findings == []


def test_bounds_checker_scoped_to_serving_paths(tmp_path):
    """An unbounded queue OUTSIDE net//http_server.py/relay.py/
    core/tenancy.py is not this checker's business (internal planes are
    bounded upstream)."""
    src = tmp_path / "beacon_thing.py"
    src.write_text("import queue\nQ = queue.Queue()\n")
    report = run_vet([str(src)], checkers=by_names(["bounds"]))
    assert report.findings == []


def test_bounds_checker_covers_tenancy(tmp_path):
    """ISSUE 15: the tenant registry joined the bounds scope — the
    seeded fixture violation at rel path core/tenancy.py is caught, the
    bounded constructs stay silent, and the justified spool is a
    suppression, not a finding."""
    report = _fixture_report("bounds")
    codes = _codes(report, "core/tenancy.py")
    assert ("core/tenancy.py", "bounds-unbounded-queue") in codes
    lines = {f.line for f in report.findings
             if f.path == "core/tenancy.py"}
    assert len(lines) == 1, sorted(lines)       # exactly the seeded BAD
    assert len([f for f in report.suppressed
                if f.path == "core/tenancy.py"]) == 1


def test_wait_checker_exempts_test_code(tmp_path):
    """The discipline targets production code: tests wait on work they
    control, bounded by pytest's own timeout machinery."""
    src = tmp_path / "test_something.py"
    src.write_text(
        "def test_x(fut):\n"
        "    return fut.result()\n")
    report = run_vet([str(src)], checkers=by_names(["wait"]))
    assert report.findings == []


def test_recompile_checker_catches_fixture():
    report = _fixture_report("recompile")
    codes = _codes(report)
    assert ("ops/recompile_bad.py",
            "recompile-data-dependent-static") in codes
    assert ("ops/recompile_bad.py", "recompile-unhashable-static") in codes
    assert ("ops/recompile_bad.py",
            "recompile-data-dependent-flavor") in codes
    assert ("ops/recompile_bad.py", "recompile-per-call-placement") in codes
    # the unhashable DEFAULT is reported at the def, the static-args
    # summary crosses the crypto/ -> ops/ module boundary for the rest
    assert ("crypto/recompile_kernels.py",
            "recompile-unhashable-static") in codes
    # the placement home is exempt outside loops — but not inside one
    assert ("crypto/device_pool.py", "recompile-per-call-placement") in codes
    msgs = [f.message for f in report.findings]
    assert any(".item()" in m and "static arg `lanes`" in m for m in msgs)
    assert any("int(counts)" in m for m in msgs)
    # shape-derived and config-derived flavor constants stay silent: the
    # two seeded call-site BADs are the only `lanes` findings
    assert sum("static arg `lanes`" in m for m in msgs) == 2
    # the justified one-off mesh is a suppression, not a finding
    assert len([f for f in report.suppressed
                if f.path == "ops/recompile_bad.py"]) == 1


def test_deadline_checker_catches_fixture():
    report = _fixture_report("deadline")
    codes = _codes(report, "net/deadline_bad.py")
    assert ("net/deadline_bad.py", "deadline-unbounded-call") in codes
    assert ("net/deadline_bad.py", "deadline-not-threaded") in codes
    msgs = [f.message for f in report.findings]
    assert any("subprocess.run" in m for m in msgs)
    assert any("urlopen" in m for m in msgs)
    assert any(".communicate()" in m for m in msgs)
    assert any("omits `timeout`" in m for m in msgs)
    # bounded calls, threaded budgets, and the `or`-fallback helper stay
    # silent: exactly the four seeded BADs fire, and the helpers module
    # (timeout flows with expressions present) is clean
    lines = {f.line for f in report.findings
             if f.path == "net/deadline_bad.py"}
    assert len(lines) == 4, sorted(lines)
    assert not any(f.path == "net/deadline_helpers.py"
                   for f in report.findings)
    assert len([f for f in report.suppressed
                if f.path == "net/deadline_bad.py"]) == 1


def test_deadline_checker_covers_fleet_harness():
    """ISSUE 18: the fleet harness (tests/fleet.py, tools/fleet.py) is in
    deadline scope DESPITE living under tests/ — a wedged subprocess wait
    or accept loop must die in minutes, not hang CI.  Ordinary test
    support files keep the exemption."""
    report = _fixture_report("deadline")
    codes = _codes(report, "tests/fleet.py")
    assert ("tests/fleet.py", "deadline-unbounded-call") in codes
    msgs = [f.message for f in report.findings
            if f.path == "tests/fleet.py"]
    # the three seeded shapes: bare Popen.wait(), unbounded subprocess
    # run, and the settimeout-less accept/recv loop (accept + recv)
    assert any(".wait()" in m for m in msgs)
    assert any("subprocess.run" in m for m in msgs)
    assert any(".accept()" in m for m in msgs)
    assert any(".recv()" in m for m in msgs)
    lines = {f.line for f in report.findings if f.path == "tests/fleet.py"}
    assert len(lines) == 4, sorted(lines)
    # GoodProxy (settimeout discipline) and reap_bounded stay silent;
    # the non-fleet harness file keeps the test-code exemption entirely
    assert not any(f.path == "tests/other_harness.py"
                   for f in report.findings)


def test_threadlife_checker_catches_fixture():
    report = _fixture_report("threadlife")
    path = "core/threadlife_bad.py"
    by_code = {}
    for f in report.findings:
        if f.path == path:
            by_code.setdefault(f.code, set()).add(f.line)
    assert len(by_code["threadlife-unnamed"]) == 1
    # unregistered literal prefix + fully dynamic name
    assert len(by_code["threadlife-unregistered-name"]) == 2
    # LeakyOwner._pump (never joined), LeakyOwner._probe (join exists but
    # stop() never reaches it), NoStopOwner (no stop root at all)
    assert len(by_code["threadlife-no-join"]) == 3
    msgs = [f.message for f in report.findings if f.path == path]
    assert any("NoStopOwner" in m for m in msgs)
    # the tuple-swap + bounded-join idiom is recognized, not flagged
    assert not any("CleanOwner" in m for m in msgs)
    # unbound .start(), local started-and-dropped, and the returns_thread
    # local from make_pump()
    assert len(by_code["threadlife-orphan"]) == 3
    assert len([f for f in report.suppressed if f.path == path]) == 1


def test_metriclabel_checker_catches_fixture():
    report = _fixture_report("metriclabel")
    path = "metrics_bad.py"
    hits = [f for f in report.findings if f.path == path]
    assert hits and all(f.code == "metriclabel-unbounded" for f in hits)
    # peer_addr, the round f-string, req.url — each exactly once
    assert len(hits) == len({f.line for f in hits}) == 3, \
        sorted(f.line for f in hits)
    msgs = [f.message for f in hits]
    assert any("peer_addr" in m for m in msgs)
    assert any("req.url" in m for m in msgs)
    # bounded identifiers, literals, registered_label(), the bounded-table
    # lookup, and the one-hop bounded local all stay silent
    assert not any("beacon_id" in m or "STATE_NAMES" in m
                   or "route" in m or "lane_value" in m for m in msgs)
    assert len([f for f in report.suppressed if f.path == path]) == 1


# -- the interprocedural regression: v1 misses, v2 catches --------------------


def _fixture_module(rel):
    from drand_tpu.analysis.symbols import ModuleInfo
    full = os.path.join(FIXTURES, rel.replace("/", os.sep))
    with open(full, "r", encoding="utf-8") as f:
        return ModuleInfo(full, rel, f.read())


def test_cross_function_pair_v1_misses_v2_catches():
    """THE tentpole regression, asserted both ways: the cross-function
    fixture leaks are invisible to a v1 per-function pass (checker.check
    with no project) and caught by the v2 two-phase run."""
    from drand_tpu.analysis.checkers.clock import ClockChecker
    from drand_tpu.analysis.checkers.secrets import SecretChecker
    secret_bad = _fixture_module("crypto/secret_flow_bad.py")
    clock_bad = _fixture_module("core/clock_flow_bad.py")
    # v1: no project — per-function analysis sees opaque helper calls
    assert list(SecretChecker().check(secret_bad)) == []
    assert list(ClockChecker().check(clock_bad)) == []
    # v2: phase-1 summaries expose returns_secret / logged_params /
    # returns_wallclock across the module boundary
    report = run_vet([FIXTURES], checkers=by_names(["secret", "clock"]))
    codes = _codes(report)
    assert ("crypto/secret_flow_bad.py", "secret-in-log") in codes
    assert ("crypto/secret_flow_bad.py", "secret-interproc-log") in codes
    assert ("core/clock_flow_bad.py", "clock-interproc-call") in codes


def test_lockorder_pair_v2_misses_v3_catches():
    """The tpu-tsan tentpole regression, asserted both ways: the
    cross-module lock-order cycle, the helper-laundered write, the
    transitive sleep-under-lock, and the callback invoked under the
    registrar's lock are all invisible to the per-class v2 pass
    (checker.check with no project) and caught by the v3 project run."""
    from drand_tpu.analysis.checkers.locks import LockChecker
    mod_a = _fixture_module("core/lockorder_a.py")
    mod_b = _fixture_module("core/lockorder_b.py")
    # v2: no project — each half looks clean to the per-class analysis
    assert list(LockChecker().check(mod_a)) == []
    assert list(LockChecker().check(mod_b)) == []
    # v3: phase-1 lockset summaries expose all four seeded shapes
    report = run_vet([FIXTURES], checkers=by_names(["lock"]))
    codes = _codes(report)
    assert ("core/lockorder_a.py", "lock-helper-mutation") in codes
    assert ("core/lockorder_a.py", "lock-blocking-transitive") in codes
    assert ("core/lockorder_b.py", "lock-callback-blocking") in codes
    msgs = [f.message for f in report.findings]
    cross = [m for m in msgs if "cycle" in m and "PlacerA" in m]
    assert any("RegistryB" in m for m in cross), msgs
    # the guarded-path call (enqueue_locked) is never flagged
    assert not any("enqueue_locked" in m for m in msgs)


def test_threadlife_returns_thread_orphan_needs_project():
    """The start_made_pump leak rides on the returns_thread summary:
    v1 sees `t = make_pump(fn)` as an opaque call and stays silent."""
    from drand_tpu.analysis.checkers.threadlife import ThreadLifeChecker
    mod = _fixture_module("core/threadlife_bad.py")
    v1 = {f.line for f in ThreadLifeChecker().check(mod)
          if f.code == "threadlife-orphan"}
    report = _fixture_report("threadlife")
    v2 = {f.line for f in report.findings + report.suppressed
          if f.path == "core/threadlife_bad.py"
          and f.code == "threadlife-orphan"}
    extra = v2 - v1
    assert len(extra) == 1, (sorted(v1), sorted(v2))


def test_all_fixture_violations_found_by_full_run():
    """One full-corpus run: every checker contributes findings (no
    checker silently stopped matching its fixture)."""
    report = run_vet([FIXTURES])
    by_checker = report.counts()
    for name in checker_names():
        assert by_checker.get(name, 0) > 0, (
            f"checker {name!r} found nothing in its fixture\n"
            + report.render_text())


# -- framework machinery ------------------------------------------------------


def test_suppression_scoping(tmp_path):
    src = tmp_path / "scoped.py"
    src.write_text(
        "import time\n"
        "def a():\n"
        "    return time.time()\n"
        "def b():\n"
        "    return time.time()  # tpu-vet: disable=lock\n")
    report = run_vet([str(src)], checkers=by_names(["clock"]))
    # a wrong checker token does NOT suppress a clock finding
    assert len(report.findings) == 2


def test_file_level_suppression(tmp_path):
    src = tmp_path / "filewide.py"
    src.write_text(
        "# tpu-vet: disable-file=clock\n"
        "import time\n"
        "def a():\n"
        "    return time.time()\n")
    report = run_vet([str(src)], checkers=by_names(["clock"]))
    assert report.findings == []
    assert len(report.suppressed) == 1


def test_stale_suppression_audit(tmp_path):
    """A disable comment covering a live finding is fine; one covering
    nothing is reported stale — but only for checkers that ran."""
    src = tmp_path / "hygiene.py"
    src.write_text(
        "import time\n"
        "def a():\n"
        "    return time.time()  # tpu-vet: disable=clock\n"
        "def b():  # tpu-vet: disable=clock\n"
        "    return 2\n"
        "def c():  # tpu-vet: disable=secret\n"
        "    return 3\n")
    report = run_vet([str(src)], checkers=by_names(["clock"]))
    assert report.findings == []
    assert len(report.suppressed) == 1
    # line 4's clock token is stale; line 6's secret token is out of
    # scope for a clock-only run and must NOT be condemned
    assert len(report.stale_suppressions) == 1
    assert "hygiene.py:4" in report.stale_suppressions[0]
    assert "disable=clock" in report.stale_suppressions[0]


def test_stale_baseline_audit(tmp_path):
    """Baseline budget no current finding consumes is reported."""
    report = _fixture_report("clock")
    path = tmp_path / "base.json"
    write_baseline(str(path), report)
    baseline = load_baseline(str(path))
    baseline["gone.py|clock|clock-direct-call|phantom"] = 1
    rerun = run_vet([FIXTURES], checkers=by_names(["clock"]),
                    baseline=baseline)
    assert rerun.findings == []          # real ones all baselined
    assert rerun.stale_baseline == \
        ["gone.py|clock|clock-direct-call|phantom"]


def test_parallel_sweep_is_deterministic():
    """The forked sweep must be byte-identical to the serial one (same
    findings, same order) — force the pool on for the fixture corpus.
    Runs in a fresh interpreter: the vet CLI never imports JAX so its
    forks are safe, but THIS process has JAX loaded (multithreaded, and
    os.fork from a threaded parent can deadlock), so don't fork here."""
    probe = (
        "import sys\n"
        "sys.path.insert(0, %r)\n"
        "from drand_tpu.analysis import run_vet\n"
        "from drand_tpu.analysis import core as vet_core\n"
        "serial = run_vet([%r]).to_dict()\n"
        "vet_core._PARALLEL_MIN_FILES = 1\n"
        "import os; os.environ['TPU_VET_WORKERS'] = '2'\n"
        "parallel = run_vet([%r]).to_dict()\n"
        "assert parallel == serial, 'parallel sweep diverged from serial'\n"
        "assert serial['findings'], 'fixture corpus found nothing'\n"
        "assert 'jax' not in sys.modules\n"
    ) % (REPO, FIXTURES, FIXTURES)
    proc = subprocess.run([sys.executable, "-c", probe],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_baseline_roundtrip(tmp_path):
    """write-baseline grandfathers current findings; a NEW finding of the
    same kind elsewhere still fails."""
    report = _fixture_report("clock")
    assert report.findings
    path = str(tmp_path / "baseline.json")

    class R:     # report shim with only what write_baseline reads
        findings = report.findings
        baselined = []

    write_baseline(path, R)
    baseline = load_baseline(path)
    again = run_vet([FIXTURES], checkers=by_names(["clock"]),
                    baseline=baseline)
    assert again.findings == []
    assert len(again.baselined) == len(report.findings)
    # a fresh violation is NOT covered by the baseline
    extra = os.path.join(FIXTURES, "clock_extra_tmp.py")
    with open(extra, "w") as f:
        f.write("import time\nBAD = time.time()\n")
    try:
        third = run_vet([FIXTURES], checkers=by_names(["clock"]),
                        baseline=baseline)
        assert len(third.findings) == 1
        assert third.findings[0].path == "clock_extra_tmp.py"
    finally:
        os.unlink(extra)


def test_single_file_scan_keeps_package_path_context():
    """A single-FILE argument resolves rel against its topmost enclosing
    package, so per-changed-file invocations (pre-commit style) agree
    with the canonical directory scan: the clock checker's own allowlist
    still matches `vet.py drand_tpu/beacon/clock.py`, and a scoped
    checker still fires on a file named directly."""
    clock_py = os.path.join(PACKAGE, "beacon", "clock.py")
    report = run_vet([clock_py], checkers=by_names(["clock"]))
    assert report.findings == []        # allowlisted, not basename-blind

    resil = os.path.join(PACKAGE, "net", "resilience.py")
    from drand_tpu.analysis.core import _iter_files
    (_, rel), = _iter_files(resil, ())
    assert rel == "net/resilience.py"   # matches a drand_tpu/ dir scan

    # a SUBDIRECTORY scan is package-anchored the same way: scanning
    # drand_tpu/beacon/ must not strip the beacon/ prefix and thereby
    # flag the Clock implementations themselves
    beacon_dir = os.path.join(PACKAGE, "beacon")
    rels = {r for _, r in _iter_files(beacon_dir, ())}
    assert "beacon/clock.py" in rels
    report = run_vet([beacon_dir], checkers=by_names(["clock"]))
    assert [f for f in report.findings if f.path.endswith("clock.py")] == []


def test_unparseable_file_is_an_error_not_a_pass(tmp_path):
    src = tmp_path / "broken.py"
    src.write_text("def broken(:\n")
    report = run_vet([str(tmp_path)])
    assert not report.clean
    assert report.errors and "broken.py" in report.errors[0]


def test_generated_protos_are_excluded():
    report = run_vet([PACKAGE])
    assert not any("_pb2" in f.path
                   for f in report.findings + report.suppressed)


# -- CLI ----------------------------------------------------------------------


def _run_cli(*argv):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import vet
        return vet
    finally:
        sys.path.pop(0)


def test_cli_exit_codes_and_json(tmp_path, capsys):
    vet = _run_cli()
    # clean target -> 0
    clean = tmp_path / "clean.py"
    clean.write_text("X = 1\n")
    assert vet.main([str(clean)]) == 0
    # findings -> 1, and the JSON is machine-readable
    assert vet.main([FIXTURES, "--format", "json"]) == 1
    out = capsys.readouterr().out
    payload = json.loads(out[out.index("{"):])
    assert payload["clean"] is False
    assert payload["counts"]
    # usage errors -> 2
    assert vet.main(["/no/such/path-anywhere"]) == 2
    assert vet.main([FIXTURES, "--checkers", "nope"]) == 2
    assert vet.main([FIXTURES, "--baseline", "/no/such/baseline"]) == 2


def test_cli_sarif_output(capsys):
    vet = _run_cli()
    assert vet.main([FIXTURES, "--format", "sarif",
                     "--checkers", "deadline"]) == 1
    out = capsys.readouterr().out
    doc = json.loads(out[out.index("{"):])
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "tpu-vet"
    assert any(r["id"] == "tpu-vet/deadline-unbounded-call"
               for r in run["tool"]["driver"]["rules"])
    assert run["results"]
    for res in run["results"]:
        assert res["level"] == "error"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["region"]["startLine"] >= 1


def test_cli_changed_scopes_to_git_dirty_files(tmp_path, capsys):
    """--changed reports only git-touched files, with the committed rest
    of the tree parsed as phase-1 context (not reported)."""
    def git(*a):
        subprocess.run(["git", *a], cwd=tmp_path, check=True,
                       capture_output=True)
    git("init", "-q")
    git("config", "user.email", "vet@test")
    git("config", "user.name", "vet")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "committed.py").write_text("import time\nBAD = time.time()\n")
    git("add", ".")
    git("commit", "-qm", "seed")
    (pkg / "fresh.py").write_text("import time\nALSO_BAD = time.time()\n")

    vet = _run_cli()
    rc = vet.main([str(pkg), "--changed", "--checkers", "clock",
                   "--format", "json"])
    out = capsys.readouterr().out
    payload = json.loads(out[out.index("{"):])
    assert rc == 1
    # only the untracked file is reported; committed.py (equally in
    # violation) is context, not a finding
    assert {f["path"] for f in payload["findings"]} == {"fresh.py"}

    # a fully-committed tree reports nothing and exits 0
    git("add", ".")
    git("commit", "-qm", "fix")
    assert vet.main([str(pkg), "--changed", "--checkers", "clock"]) == 0
    capsys.readouterr()


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    vet = _run_cli()
    bl = str(tmp_path / "bl.json")
    assert vet.main([FIXTURES, "--write-baseline", bl]) == 0
    assert vet.main([FIXTURES, "--baseline", bl]) == 0
    capsys.readouterr()


def test_checker_registry_names_are_suppression_tokens():
    assert checker_names() == ["clock", "lock", "secret", "trace", "store",
                               "verifier", "wait", "bounds", "atomic",
                               "recompile", "deadline", "threadlife",
                               "metriclabel"]
    assert len(ALL_CHECKERS) == 13
    with pytest.raises(KeyError):
        by_names(["not-a-checker"])
