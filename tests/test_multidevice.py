"""Multi-device scale-out (ISSUE 11): per-handle device groups,
independent dispatch streams, group-isolated failover, and pool-wide
round-axis sharding — the CPU suite on the 8 virtual devices conftest
forces (`XLA_FLAGS=--xla_force_host_platform_device_count=8`).

Scheduler-level tests run against stub backends (no compiles); the real
jax surface is exercised placement-only (device_put, no programs) in
test_verify_service.test_device_backend_gets_group_placement_and_pool_
sharding, and the sharded RLC program itself by the heavy-bucket
test_multichip.py.
"""

import threading
import types

import numpy as np
import pytest

from drand_tpu.beacon.clock import FakeClock
from drand_tpu.crypto.device_pool import (DevicePool, GROUP_FAULTED,
                                          GROUP_HEALTHY, jax_devices)
from drand_tpu.crypto.verify_service import (LANE_BACKGROUND, LANE_LIVE,
                                             VerifyService)

SCHEME = types.SimpleNamespace(id="stub-scheme")


def pk(i: int) -> bytes:
    return bytes([i]) * 48


def stub_rule(round_, sig):
    return sig == b"sig-%d" % round_


def beacons(rng, bad=()):
    rounds = list(rng)
    sigs = [b"sig-%d" % r if r not in bad else b"forged" for r in rounds]
    return rounds, sigs, [None] * len(rounds)


class StubBackend:
    kind = "stub"

    def __init__(self):
        self.calls = []
        self.started = threading.Event()

    def verify_batch(self, rounds, sigs, prev_sigs=None):
        self.calls.append(list(rounds))
        self.started.set()
        return np.array([stub_rule(r, s) for r, s in zip(rounds, sigs)],
                        dtype=bool)


def make_service(**kw):
    kw.setdefault("clock", FakeClock(1000.0))
    kw.setdefault("pad", 8)
    kw.setdefault("background_window", 0.0)
    return VerifyService(**kw)


# -- device pool --------------------------------------------------------------


def test_pool_partitions_devices_into_groups():
    devs = jax_devices()
    assert len(devs) == 8, "conftest must force 8 virtual CPU devices"
    pool = DevicePool()                     # AUTO: one group per device
    assert pool.n_groups == 8 and pool.n_devices == 8
    assert all(g.n_devices == 1 for g in pool.groups)
    seen = [d for g in pool.groups for d in g.devices]
    assert len(set(map(id, seen))) == 8     # a partition, not copies
    quad = DevicePool(n_groups=4)
    assert quad.n_groups == 4
    assert [g.n_devices for g in quad.groups] == [2, 2, 2, 2]
    assert dict(quad.pool_sharding().mesh.shape)["round"] == 8


def test_pool_assignment_is_sticky_and_least_loaded():
    pool = DevicePool(n_groups=4)
    gids = [pool.assign(("k", i)).gid for i in range(8)]
    assert sorted(gids) == [0, 0, 1, 1, 2, 2, 3, 3]
    assert pool.assign(("k", 3)).gid == gids[3]     # sticky
    # churn rebalances: release group-0 tenants, the next handles refill it
    for i, g in enumerate(gids):
        if g == 0:
            pool.release(("k", i))
    assert pool.assign(("k", "new-a")).gid == 0
    assert pool.assign(("k", "new-b")).gid == 0
    assert pool.loads() == {0: 2, 1: 2, 2: 2, 3: 2}


def test_pool_reassign_avoids_faulted_groups():
    pool = DevicePool(n_groups=3)
    g = pool.assign("key")
    g.state = GROUP_FAULTED
    sib = pool.reassign("key")
    assert sib is not None and sib.gid != g.gid
    assert sib.state == GROUP_HEALTHY
    # all faulted -> nowhere to go
    for grp in pool.groups:
        grp.state = GROUP_FAULTED
    assert pool.reassign("key") is None


# -- k chains, k groups, overlapping windows (the ISSUE acceptance) -----------


def run_workload(svc, n_chains=8, gate_pair=None):
    """n_chains handles, one submission each; returns (handles, verdicts).
    `gate_pair` (i, j) wires chains i and j with backends that each BLOCK
    until the other's dispatch has started — resolvable only if the two
    groups' streams really dispatch concurrently."""
    handles = []
    backends = []
    for i in range(n_chains):
        b = StubBackend()
        if gate_pair is not None and i in gate_pair:
            other = gate_pair[1] if i == gate_pair[0] else gate_pair[0]

            class Gated(StubBackend):
                def __init__(self, me_i, other_i, all_backends):
                    super().__init__()
                    self.me_i, self.other_i = me_i, other_i
                    self.all = all_backends

                def verify_batch(self, rounds, sigs, prev_sigs=None):
                    self.started.set()
                    assert self.all[self.other_i].started.wait(20), (
                        "the sibling group's dispatch never started — "
                        "streams are serialized, not concurrent")
                    return super().verify_batch(rounds, sigs, prev_sigs)

            b = Gated(i, other, backends)
        backends.append(b)
        handles.append(svc.handle(SCHEME, pk(i), backend=b))
    futs = [h.submit(*beacons(range(1, 9), bad={2 + i}), lane=LANE_LIVE)
            for i, h in enumerate(handles)]
    verdicts = [f.result(30) for f in futs]
    return handles, verdicts


def test_8_handles_dispatch_through_independent_groups():
    """8 chains land on 8 distinct device groups with CONCURRENTLY
    in-flight windows (two gated chains each block until the other's
    dispatch starts — deadlock unless the streams overlap), and the
    verdicts are bit-identical to the single-group (old single-device)
    path."""
    svc = make_service()
    handles, verdicts = run_workload(svc, 8, gate_pair=(0, 5))
    st = svc.stats()
    gids = {h.gid for h in handles}
    assert len(gids) >= 2, st["group_map"]
    assert len(gids) == 8                   # AUTO: one group per device
    assert st["n_groups"] == 8 and st["n_devices"] == 8
    assert st["concurrent_streams_max"] >= 2
    # every group really dispatched (per-group streams, not one shared)
    dispatched = {g for g, info in st["groups"].items()
                  if info["dispatches"] > 0}
    assert len(dispatched) == 8
    svc.stop()

    single = make_service(device_groups=1)
    _, single_verdicts = run_workload(single, 8)
    assert single.stats()["n_groups"] == 1
    for got, want in zip(verdicts, single_verdicts):
        assert (got == want).all()          # bit-identical to 1-group path
    single.stop()


# -- group-isolated failover --------------------------------------------------


class DeadBackend(StubBackend):
    def verify_batch(self, rounds, sigs, prev_sigs=None):
        self.calls.append(list(rounds))
        raise ConnectionError("device gone")


def test_one_groups_fault_degrades_only_that_group():
    """Kill one chain's backend: it degrades to its host fallback; the
    other chains' verdicts, backend states and latency histories are
    untouched."""
    svc = make_service()
    dead, fb = DeadBackend(), StubBackend()
    h_bad = svc.handle(SCHEME, pk(0), backend=dead, fallback=fb)
    healthy = [(svc.handle(SCHEME, pk(i), backend=StubBackend()), i)
               for i in range(1, 5)]
    assert h_bad.verify_batch(*beacons([1, 2], bad={2})).tolist() \
        == [True, False]                    # via the fallback, requeued
    for h, i in healthy:
        assert h.verify_batch(*beacons(range(1, 5))).all()
    st = svc.stats()
    assert st["failovers"] == 1
    assert svc.degraded_backends() == [svc._slots[h_bad.key].label]
    for h, _ in healthy:
        slot = svc._slots[h.key]
        assert slot.state == "healthy"
        assert len(slot.latencies) == 1     # its own dispatch, nothing else
        assert slot.gid != h_bad.gid        # distinct failure domains
    svc.stop()


def test_group_fault_fails_over_to_sibling_group_before_host():
    """A group-backed handle (backend_factory) whose group faults is
    REBUILT on a healthy sibling group — the slot stays healthy, never
    sees the host path, and the faulted group is quarantined."""
    built = []

    def factory(group):
        b = DeadBackend() if not built else StubBackend()
        built.append((group.gid, b))
        return b

    svc = make_service(device_groups=4)
    h = svc.handle(SCHEME, pk(0), backend_factory=factory)
    old_gid = h.gid
    ok = h.verify_batch(*beacons([1, 2, 3], bad={3}))
    assert ok.tolist() == [True, True, False]
    st = svc.stats()
    assert st["migrations"] == 1
    assert st["failovers"] == 0             # host path never taken
    slot = svc._slots[h.key]
    assert slot.state == "healthy"
    assert h.gid != old_gid                 # moved to the sibling
    assert len(built) == 2 and built[1][0] == h.gid
    assert isinstance(slot.primary, StubBackend) \
        and not isinstance(slot.primary, DeadBackend)
    assert st["groups"][old_gid]["state"] == "faulted"
    assert st["groups"][h.gid]["state"] == "healthy"
    svc.stop()


def test_group_fault_degrades_to_host_when_no_healthy_sibling():
    """device_groups=1: there is no sibling — the ladder's last rung
    (host fallback) serves, exactly the pre-pool behavior."""
    fb = StubBackend()
    svc = make_service(device_groups=1)
    h = svc.handle(SCHEME, pk(0),
                   backend_factory=lambda g: DeadBackend(), fallback=fb)
    assert h.verify_batch(*beacons([1, 2])).all()
    st = svc.stats()
    assert st["migrations"] == 0 and st["failovers"] == 1
    assert fb.calls == [[1, 2]]
    assert st["groups"][0]["state"] == "faulted"
    svc.stop()


# -- pool-wide round-axis sharding for huge batches ---------------------------


class PoolStub(StubBackend):
    """Stands in for the pool-wide sharded BatchBeaconVerifier."""
    pad_to = 64


def test_huge_batch_routes_to_pool_sharded_backend():
    group_stub, pool_stub = StubBackend(), PoolStub()
    svc = make_service(shard_threshold=32)
    h = svc.handle(SCHEME, pk(0), backend=group_stub,
                   pool_backend=pool_stub)
    # under the threshold: the handle's own group serves
    assert h.verify_batch(*beacons(range(1, 11))).all()
    assert len(group_stub.calls) == 2 and not pool_stub.calls
    # at/over the threshold: ONE pool-wide dispatch (span = pool pad 64)
    big = beacons(range(1, 41), bad={7, 33})
    ok = h.submit(*big, lane=LANE_BACKGROUND).result(30)
    assert len(ok) == 40 and not ok[6] and not ok[32] and ok.sum() == 38
    assert pool_stub.calls == [list(range(1, 41))]
    assert len(group_stub.calls) == 2       # untouched by the huge batch
    st = svc.stats()
    assert st["sharded_dispatches"] == 1
    # bit-identical to the unsharded path
    svc2 = make_service()                   # no pool backend: never shards
    h2 = svc2.handle(SCHEME, pk(0), backend=StubBackend())
    want = h2.verify_batch(*big)
    assert (ok == want).all()
    svc2.stop()
    svc.stop()


def test_sharded_dispatch_fault_falls_back_to_unsharded():
    """A faulting pool-wide dispatch retries once, then the riders are
    requeued UNSHARDED on the slot's own group — requeued, never
    failed — and sharding stays off for the slot until re-promotion."""
    class DeadPool(PoolStub):
        def verify_batch(self, rounds, sigs, prev_sigs=None):
            self.calls.append(list(rounds))
            raise ConnectionError("collective wedged")

    group_stub, pool_stub = StubBackend(), DeadPool()
    svc = make_service(shard_threshold=16)
    h = svc.handle(SCHEME, pk(0), backend=group_stub,
                   pool_backend=pool_stub)
    ok = h.submit(*beacons(range(1, 21), bad={4})).result(30)
    assert len(ok) == 20 and not ok[3] and ok.sum() == 19
    assert len(pool_stub.calls) == 2        # original + the one retry
    assert [len(c) for c in group_stub.calls] == [8, 8, 4]  # unsharded
    assert not svc._slots[h.key].pool_ok
    # inside the cooldown: huge submissions skip sharding entirely
    assert h.verify_batch(*beacons(range(1, 21))).all()
    assert len(pool_stub.calls) == 2
    # past the probe-cadence cooldown sharding re-arms (one transient
    # collective fault must not pin huge batches to one group forever);
    # this pool backend still faults, so it re-disarms after its retry
    svc.clock.advance(svc.probe_interval + 1.0)
    assert h.verify_batch(*beacons(range(1, 21))).all()
    assert len(pool_stub.calls) == 4        # re-armed: original + retry
    assert not svc._slots[h.key].pool_ok    # ... and re-disarmed
    svc.stop()


# -- observability ------------------------------------------------------------


def test_stats_and_summary_carry_group_view():
    svc = make_service(device_groups=2)
    h0 = svc.handle(SCHEME, pk(0), backend=StubBackend())
    h1 = svc.handle(SCHEME, pk(1), backend=StubBackend())
    assert h0.verify_batch(*beacons([1])).all()
    assert h1.verify_batch(*beacons([2], bad={2})).tolist() == [False]
    st = svc.stats()
    assert st["n_groups"] == 2 and st["n_devices"] == 8
    assert sorted(st["group_map"].values()) == [0, 1]
    assert st["groups"][0]["devices"] == 4
    assert st["groups"][0]["dispatches"] == 1
    assert st["groups"][1]["dispatches"] == 1
    s = svc.summary()
    assert "groups=2x4dev" in s
    svc.stop()


def test_group_metrics_series_exist():
    from drand_tpu import metrics
    metrics.verify_group_devices.labels("0").set(4)
    metrics.verify_dispatches.labels("live", "3").inc()
    metrics.verify_backend_state.labels("stub:chain", "2").set(0)
    blob = metrics.scrape("private").decode()
    assert 'verify_service_group_devices{group="0"} 4.0' in blob
    assert ('verify_service_dispatches_total{group="3",lane="live"}'
            in blob)
    assert ('verify_service_backend_state{chain="stub:chain",group="2"}'
            in blob)


# -- seeded group-isolation chaos (the ISSUE 11 acceptance scenario) ----------


def test_group_isolation_chaos_scenario():
    """One group's induced device fault degrades ONLY that group: the
    victim chain migrates to a healthy sibling group (host path never
    taken), every sibling chain's verdicts/state/latencies untouched."""
    from chaos import GroupIsolationScenario

    result = GroupIsolationScenario(seed=4242, chains=4).run()
    assert result.all_resolved
    assert result.verdicts_match
    assert result.victim_failed_over
    assert result.migrations >= 1 and result.failovers == 0
    assert result.victim_final_state == "healthy"   # sibling, not host
    assert result.faulted_groups == [result.victim_group]
    assert result.siblings_untouched
    assert result.ok


def test_group_isolation_without_siblings_degrades_to_host():
    from chaos import GroupIsolationScenario

    result = GroupIsolationScenario(seed=7, chains=3,
                                    siblings_available=False).run()
    assert result.all_resolved and result.verdicts_match
    assert result.migrations == 0 and result.failovers >= 1
    assert result.victim_final_state == "degraded"


def test_group_isolation_scenario_is_seed_deterministic():
    from chaos import GroupIsolationScenario

    r1 = GroupIsolationScenario(seed=99, chains=4).run()
    r2 = GroupIsolationScenario(seed=99, chains=4).run()
    assert r1.ok and r2.ok
    assert r1.victim_group == r2.victim_group
    assert r1.migrations == r2.migrations


def test_release_handle_frees_the_group_assignment():
    svc = make_service(device_groups=4)
    handles = [svc.handle(SCHEME, pk(i), backend=StubBackend())
               for i in range(4)]
    gid0 = handles[0].gid
    svc.release_handle(handles[0])
    h_new = svc.handle(SCHEME, pk(9), backend=StubBackend())
    assert h_new.gid == gid0                # churn rebalanced into the gap
    svc.stop()
