"""Resident verify service (crypto/verify_service.py): coalescer,
priority lanes, deadline flush, future fan-out, preemption, and the
dispatch-count acceptance criterion.

All scheduler tests run against injected stub backends (no jax, no
device): the service is backend-agnostic by design, and the stub records
exactly the dispatches the device would have seen.  One test pins the
fan-out verdicts against the real `HostBatchVerifier`."""

import threading
import types

import numpy as np
import pytest

from drand_tpu.beacon.clock import FakeClock
from drand_tpu.crypto.verify_service import (LANE_BACKGROUND, LANE_LIVE,
                                             VerifyService, current_service,
                                             get_service, set_service)

SCHEME = types.SimpleNamespace(id="stub-scheme")
PK = b"\x01" * 48


def stub_rule(round_, sig):
    """Deterministic per-round verdict: sig must be the round's tag."""
    return sig == b"sig-%d" % round_


class StubBackend:
    """Records every dispatch; verdicts via stub_rule.  `gate` (if set)
    blocks the FIRST dispatch until released, so tests can deterministically
    interleave live submissions with an in-flight background batch."""

    kind = "stub"

    def __init__(self, gate=None):
        self.calls = []
        self.gate = gate
        self.started = threading.Event()

    def verify_batch(self, rounds, sigs, prev_sigs=None):
        first = not self.calls
        self.calls.append(list(rounds))
        self.started.set()
        if self.gate is not None and first:
            assert self.gate.wait(10), "test gate never released"
        return np.array([stub_rule(r, s) for r, s in zip(rounds, sigs)],
                        dtype=bool)


class PipelinedStub(StubBackend):
    """Stub exposing the pack/dispatch/resolve triple so the service's
    double-buffered device path is exercised without jax."""

    pad_to = 0

    def __init__(self):
        super().__init__()
        self.stages = []

    def pack_chunk(self, rounds, sigs, prev_sigs=None):
        self.stages.append(("pack", len(rounds)))
        return list(rounds), list(sigs)

    def dispatch_packed(self, packed):
        rounds, sigs = packed
        self.calls.append(list(rounds))
        self.stages.append(("dispatch", len(rounds)))
        return all(stub_rule(r, s) for r, s in zip(rounds, sigs))

    def resolve_packed(self, packed, verdict):
        rounds, sigs = packed
        self.stages.append(("resolve", len(rounds)))
        if verdict:
            return np.ones(len(rounds), dtype=bool)
        return np.array([stub_rule(r, s) for r, s in zip(rounds, sigs)],
                        dtype=bool)


def beacons(rng, bad=()):
    rounds = list(rng)
    sigs = [b"sig-%d" % r if r not in bad else b"forged" for r in rounds]
    return rounds, sigs, [None] * len(rounds)


def make_service(**kw):
    kw.setdefault("clock", FakeClock(1000.0))
    kw.setdefault("pad", 8)
    kw.setdefault("background_window", 0.0)
    return VerifyService(**kw)


# -- coalescer ----------------------------------------------------------------


def test_coalesces_concurrent_submissions_into_one_dispatch():
    svc = make_service(background_window=100.0)
    stub = StubBackend()
    h = svc.handle(SCHEME, PK, backend=stub)
    futs = [h.submit(*beacons(range(i * 2 + 1, i * 2 + 3))) for i in range(3)]
    # nothing flushes inside the coalescing window with the batch unfilled
    assert not any(f.done() for f in futs)
    svc.clock.advance(101.0)
    outs = [f.result(timeout=10) for f in futs]
    assert all(o.all() for o in outs)
    assert len(stub.calls) == 1             # ONE dispatch for all three
    assert sorted(stub.calls[0]) == list(range(1, 7))
    assert svc.stats()["dispatches"] == 1
    svc.stop()


def test_full_batch_flushes_before_window():
    svc = make_service(pad=4, background_window=1e6)
    stub = StubBackend()
    h = svc.handle(SCHEME, PK, backend=stub)
    f = h.submit(*beacons(range(1, 5)))     # fills the pad exactly
    assert f.result(timeout=10).all()       # no clock advance needed
    svc.stop()


def test_oversize_submission_is_chunked_at_pad():
    svc = make_service(pad=8)
    stub = StubBackend()
    h = svc.handle(SCHEME, PK, backend=stub)
    ok = h.verify_batch(*beacons(range(1, 21), bad={7, 19}))
    assert len(ok) == 20
    assert not ok[6] and not ok[18]
    assert ok.sum() == 18
    assert [len(c) for c in stub.calls] == [8, 8, 4]
    svc.stop()


def test_flush_on_deadline_with_fake_clock():
    svc = make_service(background_window=50.0)
    stub = StubBackend()
    h = svc.handle(SCHEME, PK, backend=stub)
    f = h.submit(*beacons([1]))
    assert not f.done()
    svc.clock.advance(49.0)
    assert not f.done()
    svc.clock.advance(2.0)                  # window expired: flush
    assert f.result(timeout=10).all()
    svc.stop()


def test_blocking_verify_batch_skips_the_window():
    """A blocking caller (catch-up sync's serial chunk loop) cannot feed
    the coalescer while it waits, so verify_batch flushes immediately
    even with a huge window / frozen fake clock — but already-queued
    same-chain async work still rides the dispatch."""
    svc = make_service(background_window=1e6)
    stub = StubBackend()
    h = svc.handle(SCHEME, PK, backend=stub)
    rider = h.submit(*beacons([50]))        # async: parked on the window
    assert not rider.done()
    ok = h.verify_batch(*beacons([1, 2]))   # no clock advance needed
    assert ok.all()
    assert rider.result(10).all()           # coalesced into the flush
    assert len(stub.calls) == 1
    assert sorted(stub.calls[0]) == [1, 2, 50]
    svc.stop()


def test_live_lane_skips_the_coalescing_window():
    svc = make_service(background_window=1e6)
    stub = StubBackend()
    h = svc.handle(SCHEME, PK, backend=stub)
    f = h.submit(*beacons([1]), lane=LANE_LIVE)
    assert f.result(timeout=10).all()       # no clock advance needed
    svc.stop()


def test_fanout_slices_match_requests():
    svc = make_service(background_window=100.0)
    stub = StubBackend()
    h = svc.handle(SCHEME, PK, backend=stub)
    f1 = h.submit(*beacons([1, 2, 3], bad={2}))
    f2 = h.submit(*beacons([10, 11]))
    f3 = h.submit(*beacons([20], bad={20}))
    svc.clock.advance(101.0)
    assert f1.result(10).tolist() == [True, False, True]
    assert f2.result(10).tolist() == [True, True]
    assert f3.result(10).tolist() == [False]
    assert len(stub.calls) == 1
    svc.stop()


def test_empty_submission_resolves_immediately():
    svc = make_service()
    h = svc.handle(SCHEME, PK, backend=StubBackend())
    assert h.verify_batch([], []).shape == (0,)
    svc.stop()


def test_distinct_chains_do_not_merge():
    svc = make_service(background_window=100.0)
    s1, s2 = StubBackend(), StubBackend()
    h1 = svc.handle(SCHEME, PK, backend=s1)
    h2 = svc.handle(SCHEME, b"\x02" * 48, backend=s2)
    f1 = h1.submit(*beacons([1, 2]))
    f2 = h2.submit(*beacons([3, 4]))
    svc.clock.advance(101.0)
    assert f1.result(10).all() and f2.result(10).all()
    assert s1.calls == [[1, 2]] and s2.calls == [[3, 4]]
    svc.stop()


# -- double-buffered device path ----------------------------------------------


def test_pipelined_backend_runs_pack_dispatch_resolve():
    svc = make_service(pad=8)
    stub = PipelinedStub()
    h = svc.handle(SCHEME, PK, backend=stub)
    ok = h.verify_batch(*beacons(range(1, 21), bad={5}))
    assert len(ok) == 20 and not ok[4] and ok.sum() == 19
    assert [len(c) for c in stub.calls] == [8, 8, 4]
    kinds = [k for k, _ in stub.stages]
    assert kinds.count("pack") == 3
    # pack timing races the service thread (that's the point of the double
    # buffer), but dispatch/resolve order is deterministic: chunk 1 only
    # resolves AFTER chunk 2 is already dispatched
    assert [k for k in kinds if k != "pack"] == [
        "dispatch", "dispatch", "resolve", "dispatch", "resolve", "resolve"]
    svc.stop()


# -- priority lanes / preemption ----------------------------------------------


def test_live_preempts_background_at_chunk_boundary():
    gate = threading.Event()
    stub = StubBackend(gate=gate)
    svc = make_service(pad=4)
    h = svc.handle(SCHEME, PK, backend=stub)
    order = []

    bg = h.submit(*beacons(range(1, 13)))   # 3 chunks of 4
    assert stub.started.wait(10)            # chunk 1 is on the "device"
    live_call = svc.submit_call(lambda: order.append("live-call") or True,
                                lane=LANE_LIVE)
    live_batch = h.submit(*beacons([100]), lane=LANE_LIVE)
    gate.set()                              # let chunk 1 finish
    assert live_call.result(10) is True
    assert live_batch.result(10).all()
    assert bg.result(10).all()
    # the live work ran BETWEEN background chunks, not after them all
    live_pos = stub.calls.index([100])
    assert 0 < live_pos < len(stub.calls) - 1
    assert svc.stats()["preemptions"] >= 1
    svc.stop()


def test_chaos_background_scan_and_live_partials_contend():
    """A background integrity-scan stream and live partial-aggregation
    calls contend for the service; verdicts stay correct, every future
    resolves, and live work is never starved behind the whole scan."""
    gate = threading.Event()
    stub = StubBackend(gate=gate)
    # ONE device group: the contention this test exercises only exists
    # inside a single dispatch stream — with k groups the live calls
    # round-robin onto sibling streams instead (test_multidevice covers
    # that concurrency)
    svc = make_service(pad=8, device_groups=1)
    h = svc.handle(SCHEME, PK, backend=stub)

    scan_futs = [h.submit(*beacons(range(100 * i, 100 * i + 24), bad={100 * i}))
                 for i in range(4)]         # 96 rounds -> 12 chunks
    assert stub.started.wait(10)
    live_done = []
    partial = svc.partials_factory(
        lambda scheme, poly, n: types.SimpleNamespace(
            verify=lambda msg, ps: live_done.append(len(ps)) or
            [True] * len(ps)))(SCHEME, None, 3)
    results = []
    threads = [threading.Thread(
        target=lambda: results.append(partial.verify(b"m", [b"p1", b"p2"])))
        for _ in range(3)]
    for t in threads:
        t.start()
    gate.set()
    for t in threads:
        t.join(timeout=20)
    assert not any(t.is_alive() for t in threads)
    assert results == [[True, True]] * 3 and live_done == [2, 2, 2]
    for i, f in enumerate(scan_futs):
        ok = f.result(20)
        assert len(ok) == 24 and not ok[0] and ok.sum() == 23
    st = svc.stats()
    assert st["preemptions"] >= 1
    # live calls ran before the final background chunk
    total_calls = len(stub.calls)
    assert total_calls >= 12
    svc.stop()


# -- the dispatch-count acceptance criterion ----------------------------------


def test_mixed_workload_fewer_dispatches_than_per_consumer_baseline():
    """ISSUE 6 acceptance: integrity scan + simulated live partials +
    client verifies through the service issue measurably fewer dispatches
    than the per-consumer baseline (one dispatch per submission), with
    identical verdicts."""
    svc = make_service(pad=64, background_window=100.0)
    stub = StubBackend()
    h = svc.handle(SCHEME, PK, backend=stub)

    workload = []       # (rounds, sigs, prevs) per submission
    # integrity scan: 4 chunks of 16
    for i in range(4):
        workload.append(beacons(range(i * 16 + 1, i * 16 + 17),
                                bad={i * 16 + 3}))
    # client verifies: 6 small sweeps
    for i in range(6):
        workload.append(beacons([200 + i, 300 + i]))
    baseline_dispatches = len(workload)     # the old world: one each
    baseline_verdicts = [np.array([stub_rule(r, s)
                                   for r, s in zip(w[0], w[1])])
                         for w in workload]

    futs = [h.submit(*w) for w in workload]
    # live partials ride along (counted as dispatches in both worlds)
    calls = [svc.submit_call(lambda: True, lane=LANE_LIVE)
             for _ in range(3)]
    baseline_dispatches += 3
    svc.clock.advance(101.0)
    verdicts = [f.result(10) for f in futs]
    assert all(c.result(10) is True for c in calls)

    for got, want in zip(verdicts, baseline_verdicts):
        assert (got == want).all()
    st = svc.stats()
    assert st["dispatches"] < baseline_dispatches, (st, baseline_dispatches)
    # 76 background lanes at pad 64 is 2 coalesced dispatches + 3 calls
    assert st["dispatches"] <= 6
    assert st["submitted"] == 13
    svc.stop()


# -- fan-out vs the host verifier (real crypto) -------------------------------


def test_service_host_handle_matches_host_batch_verifier():
    from drand_tpu.crypto.hostverify import HostBatchVerifier
    from drand_tpu.crypto.schemes import scheme_from_name

    scheme = scheme_from_name("pedersen-bls-chained")
    sec, pub = scheme.keypair(seed=b"verify-service-test")
    pk = scheme.public_bytes(pub)
    rounds, sigs, prevs = [], [], []
    prev = b"\x42" * 32
    for r in range(1, 9):
        sig = scheme.sign(sec, scheme.digest_beacon(r, prev))
        rounds.append(r)
        sigs.append(sig)
        prevs.append(prev)
        prev = sig
    sigs[4] = sigs[3]                       # corrupt round 5

    svc = make_service(background_window=100.0)
    h = svc.handle(scheme, pk, device=False)
    assert h.kind == "host"
    f1 = h.submit(rounds[:3], sigs[:3], prevs[:3])
    f2 = h.submit(rounds[3:], sigs[3:], prevs[3:])
    svc.clock.advance(101.0)
    got = np.concatenate([f1.result(30), f2.result(30)])
    want = HostBatchVerifier(scheme, pk).verify_batch(rounds, sigs, prevs)
    assert (got == want).all()
    assert not got[4] and got.sum() == 7
    svc.stop()


# -- lifecycle / singleton ----------------------------------------------------


def test_stop_fails_pending_futures_and_rejects_new_work():
    svc = make_service(background_window=1e6)
    h = svc.handle(SCHEME, PK, backend=StubBackend())
    f = h.submit(*beacons([1]))
    svc.stop()
    with pytest.raises(RuntimeError):
        f.result(10)
    f2 = h.submit(*beacons([2]))
    with pytest.raises(RuntimeError):
        f2.result(10)


def test_singleton_install_and_clear():
    old = set_service(None)
    try:
        assert current_service() is None
        svc = get_service()
        assert get_service() is svc         # created once
        assert current_service() is svc
        summary = svc.summary()
        assert "dispatches=" in summary and "queue=" in summary
    finally:
        got = set_service(old)
        if got is not None and got is not old:
            got.stop()


def test_backend_exception_propagates_to_all_riders():
    class Boom(StubBackend):
        def verify_batch(self, rounds, sigs, prev_sigs=None):
            raise ValueError("device on fire")

    svc = make_service(background_window=100.0)
    h = svc.handle(SCHEME, PK, backend=Boom())
    f1 = h.submit(*beacons([1]))
    f2 = h.submit(*beacons([2]))
    svc.clock.advance(101.0)
    for f in (f1, f2):
        with pytest.raises(ValueError):
            f.result(10)
    svc.stop()


# -- service-owned sharding (CPU mesh) ----------------------------------------


def test_device_backend_gets_group_placement_and_pool_sharding():
    """A device handle's backend is PINNED to its device group (1 of the
    8 virtual CPU devices under the AUTO one-group-per-device layout),
    while the pool-wide sharded backend spans every device — the
    promotion of __graft_entry__.dryrun_multichip's placement to the
    serving path, now per ISSUE 11.  device_put only; no program
    compiles."""
    jax = pytest.importorskip("jax")
    from drand_tpu.crypto.device_pool import jax_devices
    if len(jax_devices()) < 2:
        pytest.skip("needs a multi-device (virtual CPU) mesh")
    from drand_tpu.crypto.schemes import scheme_from_name

    scheme = scheme_from_name("pedersen-bls-chained")
    _, pub = scheme.keypair(seed=b"shard-test")
    pk = scheme.public_bytes(pub)
    svc = make_service(pad=512)
    h = svc.handle(scheme, pk, device=True)
    assert h.kind == "device"
    ver = h.backend
    assert ver.pad_to == 512
    # group placement: exactly the group's one device
    group = svc._pool.group(h.gid)
    assert group.n_devices == 1
    arr = jax.numpy.asarray(np.zeros((512, 24), np.uint32))
    placed = ver._shard_round_axis((arr,))[0]
    assert placed.sharding.device_set == set(group.devices)
    # a second handle for the same chain is the SAME handle
    h2 = svc.handle(scheme, pk, device=True)
    assert h2 is h
    # the pool-wide sharded backend spans the FULL pool
    slot = svc._slots[h.key]
    assert svc._ensure_pool_backend(slot)
    pool_ver = slot.pool_backend
    assert pool_ver.pad_to == 512 * len(jax_devices())
    wide = jax.numpy.asarray(np.zeros((pool_ver.pad_to, 24), np.uint32))
    placed = pool_ver._shard_round_axis((wide,))[0]
    assert dict(placed.sharding.mesh.shape)["round"] == len(jax_devices())
    svc.stop()


# -- the device failure domain ------------------------------------------------
# watchdog deadlines, retry-once + atomic failover, requeue-not-fail,
# canary re-promotion, per-chunk error containment (ISSUE 7)


import time  # noqa: E402  (test code; real-time waits on service threads)


class FlakyBackend(StubBackend):
    """Raises on every dispatch until `healed` is set."""

    def __init__(self):
        super().__init__()
        self.healed = threading.Event()
        self.attempts = 0

    def verify_batch(self, rounds, sigs, prev_sigs=None):
        self.attempts += 1
        if not self.healed.is_set():
            raise ConnectionError("device unreachable")
        return super().verify_batch(rounds, sigs, prev_sigs)


def test_failing_chunk_contained_to_its_callers():
    """The r7 containment regression: two coalesced callers, one poisoned
    chunk — only the overlapping caller sees the exception, the other
    rider gets its verdicts (no fallback configured here, so the error
    surfaces instead of failing over)."""
    class PoisonChunk(StubBackend):
        def verify_batch(self, rounds, sigs, prev_sigs=None):
            if 3 in rounds:
                raise ValueError("poisoned chunk")
            return super().verify_batch(rounds, sigs, prev_sigs)

    svc = make_service(pad=4, background_window=100.0)
    h = svc.handle(SCHEME, PK, backend=PoisonChunk())
    f1 = h.submit(*beacons([1, 2, 3, 4]))       # fills (poisoned) chunk 1
    f2 = h.submit(*beacons([10, 11]))           # rides in clean chunk 2
    svc.clock.advance(101.0)
    with pytest.raises(ValueError):
        f1.result(10)
    assert f2.result(10).tolist() == [True, True]
    svc.stop()


def test_raise_failover_swaps_to_fallback_and_requeues():
    """raise-on-dispatch: one strike (suspect) + one retry, then the
    backend is swapped to the fallback and the requests REQUEUED — the
    blocking caller resolves with correct verdicts, no exception."""
    svc = make_service(pad=8)
    dev, fb = FlakyBackend(), StubBackend()
    h = svc.handle(SCHEME, PK, backend=dev, fallback=fb)
    ok = h.verify_batch(*beacons([1, 2, 3], bad={2}))
    assert ok.tolist() == [True, False, True]
    assert dev.attempts == 2                    # original + the one retry
    assert fb.calls == [[1, 2, 3]]
    st = svc.stats()
    assert st["failovers"] == 1
    assert list(st["backends"].values()) == ["degraded"]
    assert "DEGRADED" in svc.summary()
    svc.stop()


def test_wrong_shape_result_is_a_fault_and_fails_over():
    """A poisoned device that ANSWERS with a wrong-shape verdict is a
    backend fault, not a caller error."""
    class Poisoned(StubBackend):
        def verify_batch(self, rounds, sigs, prev_sigs=None):
            return super().verify_batch(rounds, sigs, prev_sigs)[:-1]

    svc = make_service(pad=8)
    fb = StubBackend()
    h = svc.handle(SCHEME, PK, backend=Poisoned(), fallback=fb)
    ok = h.verify_batch(*beacons([1, 2, 3]))
    assert ok.all()
    assert fb.calls == [[1, 2, 3]]
    assert svc.stats()["failovers"] == 1
    svc.stop()


def test_watchdog_abandons_hung_dispatch_and_fails_over():
    """hang-forever: the first trip marks the backend suspect and
    requeues on the device (the retry), the second trip degrades to the
    fallback — the caller's future resolves, never an exception, and the
    wedged dispatch threads are abandoned, not waited on."""
    class HangingBackend(StubBackend):
        def __init__(self):
            super().__init__()
            self.release = threading.Event()
            self.hangs = 0

        def verify_batch(self, rounds, sigs, prev_sigs=None):
            self.hangs += 1
            self.started.set()
            self.release.wait(30)
            raise ConnectionError("hung dispatch released")

    svc = make_service(pad=8, watchdog_floor=10.0)
    dev, fb = HangingBackend(), StubBackend()
    h = svc.handle(SCHEME, PK, backend=dev, fallback=fb)
    f = h.submit(*beacons([1, 2]), lane=LANE_LIVE)
    assert dev.started.wait(10)
    svc.clock.advance(11.0)         # trip 1: suspect, retry on the device
    deadline = time.monotonic() + 10
    while dev.hangs < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert dev.hangs == 2
    svc.clock.advance(11.0)         # trip 2: degrade, requeue on fallback
    assert f.result(10).tolist() == [True, True]
    assert fb.calls == [[1, 2]]
    st = svc.stats()
    assert st["watchdog_trips"] == 2
    assert st["failovers"] == 1
    dev.release.set()               # free the abandoned dispatch threads
    svc.stop()


def test_watchdog_deadline_derives_from_latency_history():
    svc = make_service(watchdog_floor=0.5, watchdog_factor=4.0)
    h = svc.handle(SCHEME, PK, backend=StubBackend(), fallback=StubBackend())
    slot = svc._slots[h.key]
    assert svc._deadline_for(slot) == 0.5       # no history: the floor
    slot.latencies.extend([0.1, 0.2, 1.0])
    assert svc._deadline_for(slot) == pytest.approx(4.0)   # factor * p99
    slot.latencies.clear()
    slot.latencies.extend([0.01] * 50)
    assert svc._deadline_for(slot) == 0.5       # floor covers cold compiles
    svc.stop()


def test_probe_repromotes_after_recovery():
    svc = make_service(pad=8, probe_interval=5.0)
    dev, fb = FlakyBackend(), StubBackend()
    h = svc.handle(SCHEME, PK, backend=dev, fallback=fb)
    dev.healed.set()
    assert h.verify_batch(*beacons([1, 2])).all()   # healthy; sample stashed
    dev.healed.clear()
    assert h.verify_batch(*beacons([3, 4])).all()   # fails over
    slot = svc._slots[h.key]
    assert slot.state == "degraded"
    dev.healed.set()                                # the device is back
    # advance the fake clock INSIDE the wait loop (the chaos-scenario
    # pattern): a single up-front advance races the probe thread
    # computing its wait target, parking it on the 60 s real cap
    deadline = time.monotonic() + 10
    while slot.state != "healthy" and time.monotonic() < deadline:
        svc.clock.advance(svc.probe_interval + 1.0)
        time.sleep(0.02)
    assert slot.state == "healthy"
    before = len(dev.calls)
    assert h.verify_batch(*beacons([5])).all()
    assert len(dev.calls) > before                  # device serves again
    assert svc.stats()["promotions"] == 1
    svc.stop()


def test_probe_rejects_wrong_verdict_device():
    """Re-promotion requires the canary to MATCH the stashed known-good
    verdict: a device that answers but answers wrong stays degraded."""
    class LyingBackend(StubBackend):
        def __init__(self):
            super().__init__()
            self.mode = "ok"

        def verify_batch(self, rounds, sigs, prev_sigs=None):
            if self.mode == "raise":
                raise ConnectionError("down")
            out = super().verify_batch(rounds, sigs, prev_sigs)
            return ~out if self.mode == "lie" else out

    svc = make_service(pad=8, probe_interval=5.0)
    dev, fb = LyingBackend(), StubBackend()
    h = svc.handle(SCHEME, PK, backend=dev, fallback=fb)
    assert h.verify_batch(*beacons([1, 2])).all()   # sample: round 1 -> True
    dev.mode = "raise"
    assert h.verify_batch(*beacons([3, 4])).all()   # degrade (via fallback)
    slot = svc._slots[h.key]
    assert slot.state == "degraded"
    dev.mode = "lie"                                # answers, wrongly
    svc.clock.advance(6.0)
    time.sleep(0.5)                                 # let the probe run
    assert slot.state in ("degraded", "probing")
    assert svc.stats()["promotions"] == 0
    svc.stop()


def test_partials_fall_back_to_host_factory_on_device_failure():
    """Live partial aggregation survives device loss: the opaque call is
    retried once, then the lane verifier falls back to the host factory
    instead of costing the round."""
    svc = make_service()
    calls = {"dev": 0, "host": 0}

    def dev_factory(scheme, poly, n):
        def verify(msg, ps):
            calls["dev"] += 1
            raise ConnectionError("device gone")
        return types.SimpleNamespace(verify=verify, kind="device")

    def host_factory(scheme, poly, n):
        def verify(msg, ps):
            calls["host"] += 1
            return [True] * len(ps)
        return types.SimpleNamespace(verify=verify, kind="host")

    pv = svc.partials_factory(dev_factory, fallback_factory=host_factory)(
        SCHEME, None, 3)
    assert pv.verify(b"m", [b"p1", b"p2"]) == [True, True]
    assert calls["dev"] == 2 and calls["host"] == 1
    svc.stop()


def test_service_threads_are_named_and_reaped():
    svc = make_service()
    h = svc.handle(SCHEME, PK, backend=StubBackend())
    assert h.verify_batch(*beacons([1])).all()
    sched = svc._streams[h.gid].thread
    wd = svc._watchdog_thread
    assert sched.name == f"verify-scheduler-g{h.gid}"
    assert wd.name == "verify-watchdog"
    svc.stop()
    sched.join(5)
    wd.join(5)
    assert not sched.is_alive() and not wd.is_alive()


# -- seeded device-fault chaos (ISSUE 7 acceptance) ---------------------------


@pytest.fixture(scope="module")
def chaos_chain():
    from chaos import TrueChain
    return TrueChain(n=24)


def test_device_flap_chaos_scenario(chaos_chain):
    """The acceptance scenario: mixed live/background workload through a
    flapping device — every future resolves, verdicts identical to a
    host-only run, failover within one watchdog deadline, re-promotion
    after recovery, then the device serves again."""
    from chaos import DeviceChaosScenario

    result = DeviceChaosScenario(seed=1234, rounds=24,
                                 chain=chaos_chain).run()
    assert result.all_resolved
    assert result.verdicts_match_host
    assert result.failovers >= 1
    assert result.failover_latency is not None
    assert result.failover_latency <= result.deadline
    assert result.repromoted and result.final_state == "healthy"
    assert result.device_served_after_recovery
    assert result.ok


def test_device_flap_scenario_is_seed_deterministic(chaos_chain):
    from chaos import DeviceChaosScenario

    r1 = DeviceChaosScenario(seed=77, chain=chaos_chain).run()
    r2 = DeviceChaosScenario(seed=77, chain=chaos_chain).run()
    assert r1.ok and r2.ok
    assert r1.failovers == r2.failovers
    assert r1.verdicts_match_host and r2.verdicts_match_host


def test_device_death_mid_catchup_sync_converges_via_host(chaos_chain):
    """Kill the device backend mid-catch-up-sync on a 3-node network:
    the sync plane must converge through the host failover path before
    the round deadline."""
    from chaos import DeviceFailoverSyncScenario

    result = DeviceFailoverSyncScenario(seed=99, rounds=24,
                                        chain=chaos_chain).run()
    assert result.converged
    assert result.degraded                  # the device really died mid-sync
    assert not result.faulty_after_sync
    assert result.elapsed <= result.period
    assert result.ok
