"""Chain-integrity subsystem (chain/integrity.py + SyncManager.heal +
tools/chain_doctor.py): seeded at-rest storage faults are detected,
quarantined, repaired from peers, and the post-repair full-crypto rescan
is clean — all with a fake clock and in-memory peers (zero network I/O).
"""

import os
import sys

import pytest

from drand_tpu.chain.beacon import Beacon, genesis_beacon
from drand_tpu.chain.integrity import (INVALID_SIG, MALFORMED, MISSING,
                                       UNLINKED, IntegrityScanner)
from drand_tpu.chain.memdb import MemDBStore
from drand_tpu.chain.sqlitedb import SqliteStore
from drand_tpu.crypto.hostverify import HostBatchVerifier

from chaos import (BIT_FLIP, DELETED_ROW, TORN_WRITE, StorageChaosScenario,
                   StorageFaultPlan, TrueChain, inject_storage_faults,
                   stable_seed)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

N = 24

pytestmark = pytest.mark.storage


@pytest.fixture(scope="module")
def chain():
    return TrueChain(n=N)


def _seeded_store(chain, store=None, upto=N, genesis=False):
    store = store if store is not None else MemDBStore(buffer_size=100)
    if genesis:
        store.put(genesis_beacon(chain.genesis_seed))
    for r in range(1, upto + 1):
        store.put(chain.beacons[r])
    return store


def _scanner(chain, store, verifier=None, chunk=8):
    return IntegrityScanner(
        store, chain.scheme,
        verifier=verifier or HostBatchVerifier(chain.scheme, chain.public),
        genesis_seed=chain.genesis_seed, chunk=chunk,
        beacon_id="test-integrity")


# ---------------------------------------------------------------------------
# scanner unit tests
# ---------------------------------------------------------------------------


def test_scan_clean_chain_memdb_and_sqlite(chain, tmp_path):
    for store in (_seeded_store(chain),
                  _seeded_store(chain, SqliteStore(str(tmp_path / "c.db")),
                                genesis=True)):
        report = _scanner(chain, store).scan(mode="full")
        assert report.clean
        assert report.scanned == N
        assert report.upto == N
        store.close()


def test_scan_empty_store_is_clean(chain):
    report = _scanner(chain, MemDBStore(buffer_size=100)).scan(mode="full")
    assert report.clean and report.scanned == 0


def test_scan_empty_store_with_upto_flags_all_missing(chain):
    """A wiped store is NOT clean when the caller names a target: every
    round up to `upto` is a MISSING finding (full truncation must not
    scan healthy)."""
    report = _scanner(chain, MemDBStore(buffer_size=100)).scan(
        mode="full", upto=7)
    assert not report.clean
    assert report.rounds(MISSING) == list(range(1, 8))


def test_scan_flags_each_fault_kind(chain):
    store = _seeded_store(chain)
    # deterministic handcrafted faults at known rounds
    store.delete(5)                                     # hole
    b9 = store.get(9)
    store.delete(9)
    store.put(Beacon(round=9, signature=b9.signature[:40],
                     previous_sig=b9.previous_sig))     # torn write
    b14 = store.get(14)
    sig = bytearray(b14.signature)
    sig[7] ^= 0x10
    store.delete(14)
    store.put(Beacon(round=14, signature=bytes(sig),
                     previous_sig=b14.previous_sig))    # bit flip
    report = _scanner(chain, store).scan(mode="full")
    assert 5 in report.rounds(MISSING)
    assert 9 in report.rounds(MALFORMED)
    assert 14 in report.rounds(INVALID_SIG)
    # the round ABOVE a corrupt row failed verification only because its
    # anchor is corrupt — unprovable (UNLINKED), not provably invalid
    assert 15 not in report.rounds(INVALID_SIG)
    assert 15 in report.rounds(UNLINKED)
    # healthy rounds away from the damage are not flagged
    for r in (2, 3, 12, 20, N):
        assert r not in report.faulty_rounds
    # missing rounds have no row to quarantine; the others do
    assert 5 not in report.quarantinable_rounds
    assert {9, 14} <= set(report.quarantinable_rounds)


def test_scan_linkage_mode_needs_no_verifier(chain):
    store = _seeded_store(chain)
    store.delete(7)
    scanner = IntegrityScanner(store, chain.scheme,
                               genesis_seed=chain.genesis_seed)
    report = scanner.scan(mode="linkage")
    assert report.rounds(MISSING) == [7]
    assert report.verifier == "none"
    with pytest.raises(ValueError):
        scanner.scan(mode="full")      # full mode requires a verifier


def test_scan_unlinked_explicit_previous(chain):
    """A stored previous_sig that contradicts the previous row's stored
    signature is flagged UNLINKED even when the signature itself is
    genuine (full-beacon stores like memdb persist previous_sig and it
    can rot independently)."""
    store = _seeded_store(chain)
    b10 = store.get(10)
    store.delete(10)
    store.put(Beacon(round=10, signature=b10.signature,
                     previous_sig=b"\x13" * 96))
    report = _scanner(chain, store).scan(mode="full")
    assert 10 in report.rounds(UNLINKED)


def test_scan_upto_extends_past_head(chain):
    """A truncated chain (deleted tail) is only visible when the caller
    says how long the chain SHOULD be."""
    store = _seeded_store(chain, upto=N - 3)
    report = _scanner(chain, store).scan(mode="full", upto=N)
    assert report.rounds(MISSING) == [N - 2, N - 1, N]


def test_quarantine_deletes_only_bad_rows(chain):
    store = _seeded_store(chain)
    store.delete(5)
    b9 = store.get(9)
    store.delete(9)
    store.put(Beacon(round=9, signature=b9.signature[:40],
                     previous_sig=b9.previous_sig))
    scanner = _scanner(chain, store)
    report = scanner.scan(mode="full")
    deleted = scanner.quarantine(report)
    assert 9 in deleted and 5 not in deleted
    with pytest.raises(Exception):
        store.get(9)
    assert store.get(2).signature == chain.beacons[2].signature


def test_quarantine_plain_list_skips_absent_rounds(chain):
    """A plain round list (daemon check-chain path) may include rounds
    that were never on disk; they must not count as quarantined (engines
    no-op missing deletes)."""
    from drand_tpu.metrics import integrity_quarantined

    store = _seeded_store(chain)
    store.delete(6)                     # 6 is already gone
    scanner = IntegrityScanner(store, chain.scheme,
                               beacon_id="test-quarantine-plain")
    before = integrity_quarantined.labels(
        "test-quarantine-plain")._value.get()
    deleted = scanner.quarantine([3, 6])
    assert deleted == [3]
    assert integrity_quarantined.labels(
        "test-quarantine-plain")._value.get() == before + 1


# ---------------------------------------------------------------------------
# the acceptance scenario: 3 nodes, seeded at-rest faults (torn write +
# bit flip + deleted row), zero network I/O
# ---------------------------------------------------------------------------


def test_storage_chaos_detect_quarantine_repair_converge(chain):
    scenario = StorageChaosScenario(seed=42, n_nodes=3, rounds=N,
                                    chain=chain)
    result = scenario.run()
    assert sorted(result.injected.values()) == sorted(
        [TORN_WRITE, BIT_FLIP, DELETED_ROW])
    assert result.all_detected, (result.injected, result.detected_rounds)
    assert result.unrepaired == []
    assert result.rescan_clean
    assert result.converged
    assert result.ok


def test_storage_chaos_deterministic_replay(chain):
    r1 = StorageChaosScenario(seed=7, rounds=N, chain=chain).run()
    r2 = StorageChaosScenario(seed=7, rounds=N, chain=chain).run()
    assert r1.injected == r2.injected
    assert r1.detected_rounds == r2.detected_rounds
    assert r1.chain_digest == r2.chain_digest
    # a different seed corrupts different rounds
    r3 = StorageChaosScenario(seed=8, rounds=N, chain=chain).run()
    assert r3.injected != r1.injected


def test_fault_plan_is_pure_function_of_seed():
    p = StorageFaultPlan(seed=stable_seed(3, "x"), torn_writes=2,
                         bit_flips=2, deleted_rows=1)
    assert p.assign(50) == p.assign(50)
    assert len(p.assign(50)) == 5


# ---------------------------------------------------------------------------
# sqlite end-to-end + the chain-doctor CLI (device verifier path)
# ---------------------------------------------------------------------------


def _doctor_db(chain, tmp_path, name="chain.db", faults=None):
    store = SqliteStore(str(tmp_path / name))
    _seeded_store(chain, store, genesis=True)
    if faults:
        inject_storage_faults(store, faults, N)
    store.close()
    return str(tmp_path / name)


def test_chain_doctor_scan_clean_uses_device_verifier(chain, tmp_path):
    """Acceptance: `chain_doctor.py scan` on an intact chain reports 0
    findings THROUGH the batched device verifier, proven by the
    chain_integrity_beacons_scanned{verifier="device"} counter."""
    from drand_tpu.metrics import integrity_beacons_scanned
    import chain_doctor

    db = _doctor_db(chain, tmp_path)
    counter = integrity_beacons_scanned.labels("default", "device",
                                               "startup")
    before = counter._value.get()
    # chunk 8 keeps the device pass on the pad-8 pipeline shape the batch
    # suite already compiles (cold XLA compiles are minutes on 2 CPU cores)
    sys_argv = ["chain_doctor.py", "scan", "--db", db,
                "--scheme", chain.scheme.id,
                "--pubkey", chain.public.hex(),
                "--genesis-seed", chain.genesis_seed.hex(),
                "--chunk", "8"]
    old = sys.argv
    sys.argv = sys_argv
    try:
        rc = chain_doctor.main()
    finally:
        sys.argv = old
    assert rc == 0
    assert counter._value.get() == before + N


def test_chain_doctor_repair_from_db(chain, tmp_path):
    """repair --from-db: corrupt chain + healthy backup -> clean rescan."""
    import chain_doctor

    bad = _doctor_db(chain, tmp_path, "bad.db",
                     faults=StorageFaultPlan(seed=stable_seed(5, "dr")))
    good = _doctor_db(chain, tmp_path, "good.db")
    old = sys.argv
    sys.argv = ["chain_doctor.py", "repair", "--db", bad,
                "--scheme", chain.scheme.id,
                "--pubkey", chain.public.hex(),
                "--genesis-seed", chain.genesis_seed.hex(),
                "--upto", str(N), "--host", "--from-db", good]
    try:
        rc = chain_doctor.main()
    finally:
        sys.argv = old
    assert rc == 0
    store = SqliteStore(bad)
    for r in range(1, N + 1):
        assert store.get(r).signature == chain.beacons[r].signature
    store.close()


def test_chain_doctor_repair_linkage_mode(chain, tmp_path):
    """repair --mode linkage: the initial scan is structural-only, but the
    post-repair rescan is still full-crypto (the scanner gains the repair
    verifier instead of crashing on the hard-coded full mode)."""
    import chain_doctor

    bad = _doctor_db(chain, tmp_path, "bad.db",
                     faults=StorageFaultPlan(seed=stable_seed(6, "lk"),
                                             bit_flips=0))
    good = _doctor_db(chain, tmp_path, "good.db")
    old = sys.argv
    sys.argv = ["chain_doctor.py", "repair", "--db", bad,
                "--scheme", chain.scheme.id,
                "--pubkey", chain.public.hex(),
                "--genesis-seed", chain.genesis_seed.hex(),
                "--upto", str(N), "--host", "--mode", "linkage",
                "--from-db", good]
    try:
        rc = chain_doctor.main()
    finally:
        sys.argv = old
    assert rc == 0


def test_startup_integrity_pass_glue(chain):
    """core/beacon_process._integrity_pass (startup trigger): scan synchronously,
    quarantine, repair on a background thread — exercised against a stub
    process so it needs no DKG, with in-memory peers and a fake clock."""
    import time
    from types import SimpleNamespace

    from drand_tpu.beacon.clock import FakeClock
    from drand_tpu.beacon.sync import SyncManager
    from drand_tpu.core.beacon_process import BeaconProcess
    from drand_tpu.core.follow import FollowFacade
    from drand_tpu.log import Logger

    victim = _seeded_store(chain)
    inject_storage_faults(
        victim, StorageFaultPlan(seed=stable_seed(9, "startup")), N)
    facade = FollowFacade(victim, chain.scheme.chained, chain.genesis_seed)

    def fetch(peer, from_round):
        for r in range(from_round, N + 1):
            yield chain.beacons[r]

    syncm = SyncManager(
        chain=facade, scheme=chain.scheme, public_key_bytes=chain.public,
        period=30, clock=FakeClock(1), fetch=fetch, peers=["peer0"],
        chunk=8, verifier=HostBatchVerifier(chain.scheme, chain.public))
    scanner = _scanner(chain, victim)

    class FakeChain:
        backend = victim

        def last(self):
            return victim.last()

        def integrity_scan(self, verifier=None, mode="full", upto=None,
                           progress=None, beacon_id="default", chunk=512,
                           trigger="startup", resume=None):
            return scanner.scan(mode=mode, upto=upto or N, resume=resume)

    import threading as _threading
    bp = SimpleNamespace(
        cfg=SimpleNamespace(startup_integrity="full"),
        syncm=syncm, handler=SimpleNamespace(chain=FakeChain()),
        _lock=_threading.Lock(), _repair_thread=None,
        log=Logger(), beacon_id="startup-test", _peers=lambda: ["peer0"],
        # clock-derived expected head (the head-truncation follow-up):
        # the real method needs group timing; the stub pins it to N
        _expected_head_round=lambda: N,
        _on_sync_needed=lambda target: None,
        # resumability plumbing (the stub keeps no watermark)
        _load_scan_checkpoint=lambda: None,
        _save_scan_checkpoint=lambda ck: None)
    BeaconProcess._integrity_pass(bp)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if scanner.scan(mode="full", upto=N).clean:
            break
        time.sleep(0.05)
    assert scanner.scan(mode="full", upto=N).clean


def test_startup_scan_catches_head_truncation(chain):
    """ROADMAP follow-up: a deleted TAIL is invisible to a scan that asks
    the store its own length.  The startup pass derives the expected head
    from the clock (current_round), and a head behind it is flagged for
    CATCH-UP SYNC (one collapsing stream — ordinary downtime produces the
    same gap and must not be treated as corruption or fed to heal's
    per-round re-fetch) — instead of passing silently as clean."""
    from types import SimpleNamespace

    from drand_tpu.beacon.clock import FakeClock
    from drand_tpu.chain.timing import current_round, time_of_round
    from drand_tpu.core.beacon_process import BeaconProcess
    from drand_tpu.log import Logger

    period, genesis = 30, 1_000
    victim = _seeded_store(chain)
    for r in range(N - 2, N + 1):
        victim.delete(r)                 # the truncated tail

    # the store's own head says N-3: a store-head scan reports CLEAN
    assert _scanner(chain, victim).scan(mode="full").clean

    # the clock says we should be at round N
    now = time_of_round(period, genesis, N)
    bp = SimpleNamespace(clock=FakeClock(now),
                         group=SimpleNamespace(period=period,
                                               genesis_time=genesis))
    expected = BeaconProcess._expected_head_round(bp)
    assert expected == current_round(now, period, genesis) == N

    # the startup pass routes the missing suffix to catch-up sync
    scanner = _scanner(chain, victim)
    sync_requests = []

    class FakeChain:
        def last(self):
            return victim.last()

        def integrity_scan(self, verifier=None, mode="full", upto=None,
                           progress=None, beacon_id="default", chunk=512,
                           trigger="startup", resume=None):
            return scanner.scan(mode=mode, upto=upto, resume=resume)

    import threading as _threading
    bp_pass = SimpleNamespace(
        cfg=SimpleNamespace(startup_integrity="linkage"),
        syncm=SimpleNamespace(verifier=None),
        handler=SimpleNamespace(chain=FakeChain()),
        _lock=_threading.Lock(), _repair_thread=None,
        log=Logger(), beacon_id="truncation-test",
        _peers=lambda: [], clock=bp.clock, group=bp.group,
        _expected_head_round=lambda: expected,
        _on_sync_needed=sync_requests.append,
        _load_scan_checkpoint=lambda: None,
        _save_scan_checkpoint=lambda ck: None)
    BeaconProcess._integrity_pass(bp_pass)
    assert sync_requests == [expected]   # truncated tail -> catch-up sync

    # an up-to-date head (restart mid-round, head == expected - 1 — the
    # same grace /health applies) does NOT trip the probe
    for r in range(N - 2, N):
        victim.put(chain.beacons[r])     # restore through N-1
    sync_requests.clear()
    BeaconProcess._integrity_pass(bp_pass)
    assert sync_requests == []

    # before genesis nothing is expected (fresh network, empty store)
    bp_fresh = SimpleNamespace(clock=FakeClock(genesis - 1),
                               group=SimpleNamespace(period=period,
                                                     genesis_time=genesis))
    assert BeaconProcess._expected_head_round(bp_fresh) == 0


def test_heal_with_scan_report_quarantines_and_repairs(chain):
    """SyncManager.heal(ScanReport): quarantine metrics + repaired
    metrics + raw-store writeback."""
    from drand_tpu.beacon.clock import FakeClock
    from drand_tpu.beacon.sync import SyncManager
    from drand_tpu.core.follow import FollowFacade
    from drand_tpu.metrics import integrity_quarantined, integrity_repaired

    victim = _seeded_store(chain)
    inject_storage_faults(
        victim, StorageFaultPlan(seed=stable_seed(11, "heal")), N)
    facade = FollowFacade(victim, chain.scheme.chained, chain.genesis_seed)

    def fetch(peer, from_round):
        for r in range(from_round, N + 1):
            yield chain.beacons[r]

    syncm = SyncManager(
        chain=facade, scheme=chain.scheme, public_key_bytes=chain.public,
        period=30, clock=FakeClock(1), fetch=fetch, peers=["peer0"],
        chunk=8, verifier=HostBatchVerifier(chain.scheme, chain.public))
    scanner = _scanner(chain, victim)
    report = scanner.scan(mode="full", upto=N)
    assert not report.clean
    q_before = integrity_quarantined.labels("test-heal")._value.get()
    r_before = integrity_repaired.labels("test-heal")._value.get()
    remaining = syncm.heal(victim, report, beacon_id="test-heal")
    assert remaining == []
    assert integrity_quarantined.labels("test-heal")._value.get() > q_before
    assert integrity_repaired.labels("test-heal")._value.get() \
        == r_before + len(report.faulty_rounds)
    assert scanner.scan(mode="full", upto=N).clean


def test_heal_promotes_unprovable_successor_without_refetch(chain):
    """Two-phase quarantine (ROADMAP item 6): round 10 is bit-flipped, so
    round 11 — whose own bytes are intact — becomes UNPROVABLE (its
    anchor rotted).  heal must re-fetch ONLY round 10 from peers, then
    promote round 11 back from the quarantine side table once the anchor
    verifies, instead of re-downloading it."""
    from drand_tpu.beacon.clock import FakeClock
    from drand_tpu.beacon.sync import SyncManager
    from drand_tpu.core.follow import FollowFacade
    from drand_tpu.metrics import integrity_promoted

    victim = _seeded_store(chain)
    b10 = victim.get(10)
    victim.delete(10)
    sig = bytearray(b10.signature)
    sig[4] ^= 0x01
    victim.put(Beacon(round=10, signature=bytes(sig),
                      previous_sig=b10.previous_sig))

    scanner = _scanner(chain, victim)
    report = scanner.scan(mode="full", upto=N)
    assert 10 in report.rounds(INVALID_SIG)
    # round 11 is unprovable, not provably bad: every finding UNLINKED
    kinds_11 = {f.kind for f in report.findings if f.round == 11}
    assert kinds_11 == {UNLINKED}
    assert report.faulty_rounds == [10, 11]

    fetched = []

    def fetch(peer, from_round):
        fetched.append(from_round)
        for r in range(from_round, N + 1):
            yield chain.beacons[r]

    facade = FollowFacade(victim, chain.scheme.chained, chain.genesis_seed)
    syncm = SyncManager(
        chain=facade, scheme=chain.scheme, public_key_bytes=chain.public,
        period=30, clock=FakeClock(1), fetch=fetch, peers=["peer0"],
        chunk=8, verifier=HostBatchVerifier(chain.scheme, chain.public))
    p_before = integrity_promoted.labels("test-promote")._value.get()
    remaining = syncm.heal(victim, report, beacon_id="test-promote")
    assert remaining == []
    # only the provably-bad anchor hit the network
    assert 10 in fetched and 11 not in fetched
    assert integrity_promoted.labels("test-promote")._value.get() \
        == p_before + 1
    # promotion retired the tombstone and the chain re-verifies clean
    assert victim.tombstoned(11) is None
    assert victim.get(11).signature == chain.beacons[11].signature
    assert scanner.scan(mode="full", upto=N).clean


def test_heal_refetches_unprovable_when_promotion_fails(chain):
    """A tombstoned 'unprovable' row whose bytes are ACTUALLY bad (flipped
    after the anchor rotted) must fail promotion and fall through to the
    peer fetch — promotion never vouches for unverified bytes."""
    from drand_tpu.beacon.clock import FakeClock
    from drand_tpu.beacon.sync import SyncManager
    from drand_tpu.core.follow import FollowFacade

    victim = _seeded_store(chain)
    for r in (10, 11):      # flip BOTH: 11 reads unprovable but is forged
        b = victim.get(r)
        victim.delete(r)
        sig = bytearray(b.signature)
        sig[4] ^= 0x01
        victim.put(Beacon(round=r, signature=bytes(sig),
                          previous_sig=b.previous_sig))
    scanner = _scanner(chain, victim)
    report = scanner.scan(mode="full", upto=N)
    kinds_11 = {f.kind for f in report.findings if f.round == 11}
    assert kinds_11 == {UNLINKED}

    fetched = []

    def fetch(peer, from_round):
        fetched.append(from_round)
        for r in range(from_round, N + 1):
            yield chain.beacons[r]

    facade = FollowFacade(victim, chain.scheme.chained, chain.genesis_seed)
    syncm = SyncManager(
        chain=facade, scheme=chain.scheme, public_key_bytes=chain.public,
        period=30, clock=FakeClock(1), fetch=fetch, peers=["peer0"],
        chunk=8, verifier=HostBatchVerifier(chain.scheme, chain.public))
    remaining = syncm.heal(victim, report, beacon_id="test-promote-fail")
    assert remaining == []
    assert 11 in fetched        # promotion refused the forged bytes
    assert scanner.scan(mode="full", upto=N).clean


# ---------------------------------------------------------------------------
# scan resumability (ScanCheckpoint): scheduled scans resume at the clean
# prefix instead of rescanning from genesis
# ---------------------------------------------------------------------------


def test_scan_emits_and_honors_checkpoint(chain):
    """A clean scan yields a watermark; resuming from it scans only the
    delta, keeps the chained linkage anchor intact, and reports where it
    resumed."""
    store = _seeded_store(chain, upto=16)
    scanner = _scanner(chain, store)
    first = scanner.scan(mode="full", upto=16)
    assert first.clean and first.resumed_from == 0
    ck = first.checkpoint
    assert ck is not None and ck.round == 16 and ck.mode == "full"

    # idle chain (head == checkpoint): the resume must still be honored —
    # a zero delta is the cheapest scan of all, not a full-rescan trigger
    idle = scanner.scan(mode="full", upto=16, resume=ck)
    assert idle.clean and idle.resumed_from == 16 and idle.scanned == 0
    assert idle.checkpoint.round == 16

    for r in range(17, N + 1):          # the chain grows
        store.put(chain.beacons[r])
    second = scanner.scan(mode="full", upto=N, resume=ck)
    assert second.clean
    assert second.resumed_from == 16
    assert second.scanned == N - 16     # O(delta), not O(chain)
    assert second.checkpoint.round == N


def test_checkpoint_rejected_when_row_tampered(chain):
    """The watermark re-anchors against the stored row: a store rewritten
    beneath the checkpoint fails the signature-hash match and the scan
    falls back to a full walk (which then finds the tampering)."""
    store = _seeded_store(chain)
    scanner = _scanner(chain, store)
    ck = scanner.scan(mode="full", upto=16).checkpoint
    b = store.get(16)
    store.delete(16)
    store.put(Beacon(round=16, signature=b"\x00" * len(b.signature),
                     previous_sig=b.previous_sig))
    report = scanner.scan(mode="full", upto=N, resume=ck)
    assert report.resumed_from == 0     # full rescan, nothing vouched for
    assert report.scanned == N
    assert 16 in report.faulty_rounds


def test_checkpoint_freezes_at_first_finding(chain):
    """Corruption freezes the watermark at the last clean flush: the
    next resume re-examines the corrupt region instead of skipping it."""
    store = _seeded_store(chain)
    sig = store.get(18).signature
    store.delete(18)
    store.put(Beacon(round=18, signature=sig[: len(sig) // 2],
                     previous_sig=store.get(17).signature))
    scanner = _scanner(chain, store)    # chunk=8: flushes at 8, 16, 24
    report = scanner.scan(mode="full", upto=N)
    assert 18 in report.faulty_rounds
    assert report.checkpoint is not None
    assert report.checkpoint.round == 16   # last CLEAN flush boundary
    again = scanner.scan(mode="full", upto=N, resume=report.checkpoint)
    assert again.resumed_from == 16
    assert 18 in again.faulty_rounds    # the corruption is re-found


def test_linkage_checkpoint_not_honored_by_full_scan(chain):
    """A linkage-only watermark never proved any signature: a full-crypto
    scan must not skip its prefix (full checkpoints cover both modes)."""
    store = _seeded_store(chain)
    scanner = _scanner(chain, store)
    ck_link = scanner.scan(mode="linkage", upto=16).checkpoint
    assert ck_link.mode == "linkage"
    full = scanner.scan(mode="full", upto=N, resume=ck_link)
    assert full.resumed_from == 0 and full.scanned == N
    link = scanner.scan(mode="linkage", upto=N, resume=ck_link)
    assert link.resumed_from == 16      # linkage may resume from linkage


def test_scheduled_scan_resumes_and_reports_metric(chain):
    """BeaconProcess glue: trigger=scheduled loads the persisted
    watermark, passes it to the scan, records the new one, and sets the
    chain_integrity_scan_resumed_from gauge."""
    from types import SimpleNamespace

    from drand_tpu.core.beacon_process import BeaconProcess
    from drand_tpu.log import Logger
    from drand_tpu.metrics import integrity_scan_resumed_from

    store = _seeded_store(chain)
    scanner = _scanner(chain, store)
    prior = scanner.scan(mode="full", upto=16).checkpoint
    saved = {}
    scans = {}

    class FakeChain:
        def last(self):
            return store.last()

        def integrity_scan(self, verifier=None, mode="full", upto=None,
                           progress=None, beacon_id="default", chunk=512,
                           trigger="startup", resume=None):
            scans["resume"] = resume
            return scanner.scan(mode=mode, upto=upto or N, resume=resume)

    import threading as _threading
    bp = SimpleNamespace(
        cfg=SimpleNamespace(startup_integrity="full"),
        syncm=SimpleNamespace(verifier=None),
        handler=SimpleNamespace(chain=FakeChain()),
        _lock=_threading.Lock(), _repair_thread=None,
        log=Logger(), beacon_id="resume-test",
        _peers=lambda: [],
        _expected_head_round=lambda: 0,
        _on_sync_needed=lambda target: None,
        _load_scan_checkpoint=lambda: prior,
        _save_scan_checkpoint=lambda ck: saved.update(ck=ck))
    BeaconProcess._integrity_pass(bp, trigger="scheduled")
    assert scans["resume"] is prior
    assert saved["ck"].round == N       # watermark advanced
    gauge = integrity_scan_resumed_from.labels("resume-test")
    assert gauge._value.get() == 16
