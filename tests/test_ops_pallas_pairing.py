"""Pallas pairing + point-sum chain math vs host golden (see
test_ops_pallas.py for the field/ladder half; split so the two compile-heavy
halves land on different xdist workers)."""

import pytest

from drand_tpu.ops import limbs as L
from drand_tpu.ops import curve as DC
from drand_tpu.ops import pallas_field as PF
from drand_tpu.crypto.host.params import P, G1_GEN


@pytest.fixture(autouse=True)
def _interp_mode(monkeypatch):
    monkeypatch.setenv("DRAND_TPU_PALLAS", "interp")
    yield


class TestPairing:
    """Pallas pairing chain math (direct XLA lowering) vs host golden.

    Raw Miller-loop values are implementation-defined up to subfield factors
    (projective line scalings) that the final exponentiation kills, so only
    the post-final-exp value is compared."""

    def test_full_pairing_matches_host(self):
        import random
        from drand_tpu.crypto.host import curve as C
        from drand_tpu.crypto.host import pairing as HP
        from drand_tpu.crypto.host.params import R
        from drand_tpu.ops import tower as T

        random.seed(7)
        ks = [random.randrange(1, R) for _ in range(2)]
        g1s = [C.G1.mul(C.G1.gen, k) for k in ks]
        g2s = [C.G2.mul(C.G2.gen, k) for k in ks]
        px = L.encode_mont([p[0] for p in g1s])
        py = L.encode_mont([p[1] for p in g1s])
        qx = (L.encode_mont([q[0][0] for q in g2s]),
              L.encode_mont([q[0][1] for q in g2s]))
        qy = (L.encode_mont([q[1][0] for q in g2s]),
              L.encode_mont([q[1][1] for q in g2s]))
        e = PF.final_exponentiation(PF.miller_loop(px, py, (qx, qy)))
        dec = T.decode_fp12(e)
        want = [HP.pairing(p1, q2) for p1, q2 in zip(g1s, g2s)]

        def row(d, i):
            return tuple(tuple((c0[i], c1[i]) for c0, c1 in c6) for c6 in d)

        for i in range(2):
            assert row(dec, i) == want[i]

    def test_pairing_bilinearity_identity(self):
        """e(P, Q) * e(-P, Q) == 1 through the dispatched device path."""
        from drand_tpu.crypto.host import curve as C
        from drand_tpu.ops import pairing as DP

        p1 = C.G1.mul(C.G1.gen, 5)
        q2 = C.G2.mul(C.G2.gen, 7)
        px = L.encode_mont([p1[0], p1[0]])
        py = L.encode_mont([p1[1], (-p1[1]) % P])
        qx = (L.encode_mont([q2[0][0]] * 2), L.encode_mont([q2[0][1]] * 2))
        qy = (L.encode_mont([q2[1][0]] * 2), L.encode_mont([q2[1][1]] * 2))
        ok = DP.paired_product_is_one(px, py, (qx, qy), 2)
        assert bool(ok)


class TestSumPoints:
    def test_sum_tile_math_matches_host(self):
        import secrets
        from drand_tpu.crypto.host import curve as HC2
        import numpy as np2

        pts = [HC2.G1.mul(G1_GEN, secrets.randbelow(1 << 48)) for _ in range(7)]
        pts += [None]  # infinity in the batch; 8 = power-of-two width
        arrs, shape, b = PF._point_to_lanes(DC.encode_g1_points(pts))
        pt = PF._pack_point("G1", [a[:, :len(pts)] for a in arrs])
        acc = PF._sum_tile_math("G1", pt)
        got = DC.decode_g1_points(
            tuple(x[:, 0][None, :] for x in PF._flat_point(acc)))[0]
        want = None
        for p in pts:
            want = HC2.G1.add(want, p)
        assert got == want
