"""L7 client stack: decorator pipeline over a real-crypto mock chain
(the test/mock/grpcserver.go:42-327 pattern — a 1-of-1 signer whose chain
the clients verify for real).
"""

import threading
import time

import pytest

from drand_tpu.chain.beacon import Beacon
from drand_tpu.chain.info import Info
from drand_tpu.client import (CachingClient, From, GrpcTransport,
                              OptimizingClient, PollingWatcher,
                              VerifyingClient, WatchAggregator, new_client,
                              with_chain_hash, with_chain_info,
                              with_full_chain_verification)
from drand_tpu.client.interface import Client, Result
from drand_tpu.crypto.schemes import scheme_from_name

N_ROUNDS = 6


class MockChain:
    """Real-crypto 1-of-1 chain (mock/grpcserver.go generateMockData)."""

    def __init__(self, scheme_id="pedersen-bls-chained", n=N_ROUNDS,
                 genesis=1_700_000_000, period=30):
        self.scheme = scheme_from_name(scheme_id)
        sec, pub = self.scheme.keypair(seed=b"client-mock")
        self.public = self.scheme.public_bytes(pub)
        self.info = Info(public_key=self.public, period=period,
                         genesis_time=genesis, genesis_seed=b"\x07" * 32,
                         scheme=scheme_id)
        self.beacons = {}
        # chained chains anchor round 1 on the genesis seed (store.go:95-101)
        prev = self.info.genesis_seed if self.scheme.chained else None
        for r in range(1, n + 1):
            msg = self.scheme.digest_beacon(
                r, prev if self.scheme.chained else None)
            sig = self.scheme.sign(sec, msg)
            self.beacons[r] = Beacon(
                round=r, signature=sig,
                previous_sig=prev if self.scheme.chained else None)
            prev = sig


@pytest.fixture(scope="module")
def chain():
    return MockChain()


class MockSource(Client):
    """In-memory transport over a MockChain; counts fetches."""

    def __init__(self, chain: MockChain, latency: float = 0.0,
                 fail: bool = False):
        self.chain = chain
        self.latency = latency
        self.fail = fail
        self.gets = 0

    def get(self, round_: int = 0) -> Result:
        self.gets += 1
        if self.fail:
            raise ConnectionError("source down")
        if self.latency:
            time.sleep(self.latency)
        r = round_ or max(self.chain.beacons)
        if r not in self.chain.beacons:
            raise KeyError(r)
        return Result.from_beacon(self.chain.beacons[r])

    def watch(self, stop=None):
        for r in sorted(self.chain.beacons):
            if stop is not None and stop.is_set():
                return
            if self.fail:
                raise ConnectionError("source down")
            yield Result.from_beacon(self.chain.beacons[r])

    def info(self) -> Info:
        if self.fail:
            raise ConnectionError("source down")
        return self.chain.info


def test_verifying_client_accepts_valid(chain):
    vc = VerifyingClient(MockSource(chain), info=chain.info)
    r = vc.get(3)
    assert r.round == 3
    assert r.randomness == chain.beacons[3].randomness()


def test_verifying_client_rejects_corrupt(chain):
    src = MockSource(chain)
    bad = chain.beacons[2]
    corrupt = Beacon(round=2, signature=b"\x01" + bad.signature[1:],
                     previous_sig=bad.previous_sig)
    src.chain = MockChain.__new__(MockChain)
    src.chain.beacons = dict(chain.beacons)
    src.chain.beacons[2] = corrupt
    src.chain.info = chain.info
    vc = VerifyingClient(src, info=chain.info)
    with pytest.raises(ValueError):
        vc.get(2)


def test_verifying_client_strict_chained_walk(chain):
    """Strict mode verifies the whole span from the trust point — and spots
    a linkage break the per-round check can't see."""
    src = MockSource(chain)
    vc = VerifyingClient(src, info=chain.info, strict=True)
    r = vc.get(4)
    assert r.round == 4
    # walk pulled rounds 1..4; the next strict get continues from trust
    gets_before = src.gets
    vc.get(5)
    assert src.gets - gets_before <= 2  # only round 5 (+maybe latest probe)


def test_caching_client(chain):
    src = MockSource(chain)
    cc = CachingClient(VerifyingClient(src, info=chain.info))
    a = cc.get(3)
    before = src.gets
    b = cc.get(3)
    assert src.gets == before  # served from cache
    assert a == b


def test_optimizing_client_failover(chain):
    down = MockSource(chain, fail=True)
    up = MockSource(chain, latency=0.01)
    oc = OptimizingClient([down, up])
    r = oc.get(1)
    assert r.round == 1
    assert oc.info().hash() == chain.info.hash()


def test_watch_aggregator_fanout(chain):
    agg = WatchAggregator(MockSource(chain))
    got1, got2 = [], []
    stop = threading.Event()

    def sub(sink):
        for r in agg.watch(stop):
            sink.append(r.round)
            if len(sink) >= 3:
                return

    t1 = threading.Thread(target=sub, args=(got1,))
    t2 = threading.Thread(target=sub, args=(got2,))
    t1.start(), t2.start()
    t1.join(10), t2.join(10)
    stop.set()
    agg.close()
    assert len(got1) >= 3 and len(got2) >= 3


def test_new_client_pipeline_with_chain_hash(chain):
    c = new_client(From(MockSource(chain)),
                   with_chain_hash(chain.info.hash_string()))
    r = c.get(2)
    assert r.round == 2
    assert c.round_at(chain.info.genesis_time) == 1
    c.close()


def test_new_client_rejects_wrong_chain_hash(chain):
    with pytest.raises(ValueError):
        new_client(From(MockSource(chain)), with_chain_hash("ab" * 32))


def test_grpc_transport_against_daemon(chain):
    """GrpcTransport over a live Public service loopback."""
    from drand_tpu.net import Listener, services
    from drand_tpu.net import convert
    from drand_tpu.protos import drand_pb2 as pb

    class Pub:
        def public_rand(self, req, ctx):
            b = chain.beacons[req.round or N_ROUNDS]
            return convert.beacon_to_rand(b)

        def public_rand_stream(self, req, ctx):
            for r in sorted(chain.beacons):
                yield convert.beacon_to_rand(chain.beacons[r])

        def chain_info(self, req, ctx):
            return convert.info_to_proto(chain.info)

        def home(self, req, ctx):
            return pb.HomeResponse(status="ok")

    lis = Listener("127.0.0.1:0", [(services.PUBLIC, Pub())])
    lis.start()
    try:
        c = new_client(
            From(GrpcTransport(f"127.0.0.1:{lis.port}")),
            with_chain_info(chain.info))
        r = c.get(1)
        assert r.round == 1
        assert r.randomness == chain.beacons[1].randomness()
        stop = threading.Event()
        seen = []
        for res in c.watch(stop):
            seen.append(res.round)
            if len(seen) >= 2:
                stop.set()
                break
        assert seen[:2] == [1, 2]
        c.close()
    finally:
        lis.stop()


def test_verifying_client_strict_historical_get(chain):
    """After trusting a later round, strict mode must still serve earlier
    rounds (no spurious linkage failure walking 'backwards')."""
    vc = VerifyingClient(MockSource(chain), info=chain.info, strict=True)
    assert vc.get(5).round == 5          # trust point at round 5
    assert vc.get(2).round == 2          # historical get succeeds
    assert vc.get(5).round == 5          # repeated get at the trust point
