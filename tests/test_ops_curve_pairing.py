"""Device curve / hash-to-curve / pairing kernels vs the host golden code.

Host code is itself pinned by LoE mainnet known-answer vectors
(tests/test_host_crypto.py), so agreement here transitively anchors the
device kernels to real beacon data.  Compiles are cached persistently
(tests/conftest.py) — first run is slow, later runs are seconds.
"""

import random

import jax
import jax.numpy as jnp
import pytest

from drand_tpu.crypto.host import curve as C
from drand_tpu.crypto.host import h2c as HH
from drand_tpu.crypto.host import pairing as HP
from drand_tpu.crypto.host.params import DST_G1, DST_G2, R, X as BLS_X
from drand_tpu.ops import curve as DC
from drand_tpu.ops import h2c as DH
from drand_tpu.ops import limbs as L
from drand_tpu.ops import pairing as DP
from drand_tpu.ops import tower as T

random.seed(99)

KS = [random.randrange(1, R) for _ in range(4)]
G1S = [C.G1.mul(C.G1.gen, k) for k in KS]
G2S = [C.G2.mul(C.G2.gen, k) for k in KS]
DP1 = DC.encode_g1_points(G1S)
DP2 = DC.encode_g2_points(G2S)


class TestCurve:
    def test_g1_add_complete(self):
        add_j = jax.jit(DC.G1_DEV.add)
        assert DC.decode_g1_points(add_j(DP1, DC.encode_g1_points(G1S[::-1]))) == \
            [C.G1.add(a, b) for a, b in zip(G1S, G1S[::-1])]
        # P + P -> double, P + (-P) -> inf, inf identities
        assert DC.decode_g1_points(add_j(DP1, DP1)) == [C.G1.double(p) for p in G1S]
        neg = DC.encode_g1_points([C.G1.neg(p) for p in G1S])
        assert DC.decode_g1_points(add_j(DP1, neg)) == [None] * 4
        infs = DC.encode_g1_points([None] * 4)
        assert DC.decode_g1_points(add_j(infs, DP1)) == G1S
        assert DC.decode_g1_points(add_j(DP1, infs)) == G1S

    def test_g2_double(self):
        assert DC.decode_g2_points(jax.jit(DC.G2_DEV.double)(DP2)) == \
            [C.G2.double(p) for p in G2S]

    def test_scalar_mul_bits(self):
        ss = [random.randrange(R) for _ in range(4)]
        bits = DC.scalars_to_bits(ss)
        got = DC.decode_g1_points(jax.jit(DC.G1_DEV.scalar_mul_bits)(DP1, bits))
        assert got == [C.G1.mul(p, s) for p, s in zip(G1S, ss)]
        got2 = DC.decode_g2_points(jax.jit(DC.G2_DEV.scalar_mul_bits)(DP2, bits))
        assert got2 == [C.G2.mul(p, s) for p, s in zip(G2S, ss)]

    def test_g2_cofactor_clear(self):
        got = DC.decode_g2_points(jax.jit(DC.g2_clear_cofactor)(DP2))
        assert got == [C.g2_clear_cofactor(p) for p in G2S]

    def test_subgroup_checks(self):
        assert all(bool(v) for v in jax.jit(DC.g2_in_subgroup)(DP2))
        assert all(bool(v) for v in jax.jit(DC.g1_in_subgroup)(DP1))

    def test_subgroup_check_rejects_non_member(self):
        # A point on E2 but outside G2: map a field element to E2' through the
        # isogeny WITHOUT clearing the cofactor.
        u0, u1 = DH.hash_msgs_to_field_g2([b"non-member"])
        raw = jax.jit(DH.map_to_g2_jac)(u0)
        ok = jax.jit(DC.g2_in_subgroup)(raw)
        assert not bool(ok[0])

    def test_sum_points(self):
        tot = jax.jit(DC.G1_DEV.sum_points)(DP1)
        want = None
        for p in G1S:
            want = C.G1.add(want, p)
        assert DC.decode_g1_points(tot)[0] == want


class TestH2C:
    def test_g2_matches_host(self):
        msgs = [b"round-%d" % i for i in range(4)]
        u0, u1 = DH.hash_msgs_to_field_g2(msgs)
        got = DC.decode_g2_points(jax.jit(DH.hash_to_g2_jac)(u0, u1))
        assert got == [HH.hash_to_curve_g2(m, DST_G2) for m in msgs]

    def test_g1_matches_host(self):
        msgs = [b"round-%d" % i for i in range(4)]
        u0, u1 = DH.hash_msgs_to_field_g1(msgs)
        got = DC.decode_g1_points(jax.jit(DH.hash_to_g1_jac)(u0, u1))
        assert got == [HH.hash_to_curve_g1(m, DST_G1) for m in msgs]

    def test_device_h2f_full_chain_matches_host(self):
        """ISSUE 14 golden: message WORDS in, curve points out — the
        device hash-to-field stages feeding the same SSWU pipelines
        reproduce host hash_to_curve bit-for-bit on both groups."""
        from drand_tpu.ops import sha256 as SHA

        msgs = [b"device-h2f-%d" % i for i in range(3)]
        mw = SHA.pack_msgs_to_words(msgs, len(msgs[0]))

        def g2(mw_):
            u0, u1 = DH.hash_to_field_fp2_dev(mw_, len(msgs[0]), DST_G2)
            return DH.hash_to_g2_jac(u0, u1)

        def g1(mw_):
            u0, u1 = DH.hash_to_field_fp_dev(mw_, len(msgs[0]), DST_G1)
            return DH.hash_to_g1_jac(u0, u1)

        got2 = DC.decode_g2_points(jax.jit(g2)(mw))
        assert got2 == [HH.hash_to_curve_g2(m, DST_G2) for m in msgs]
        got1 = DC.decode_g1_points(jax.jit(g1)(mw))
        assert got1 == [HH.hash_to_curve_g1(m, DST_G1) for m in msgs]


class TestPairing:
    def test_pairing_matches_host(self):
        px = L.encode_mont([p[0] for p in G1S[:2]])
        py = L.encode_mont([p[1] for p in G1S[:2]])
        qx = (L.encode_mont([q[0][0] for q in G2S[:2]]),
              L.encode_mont([q[0][1] for q in G2S[:2]]))
        qy = (L.encode_mont([q[1][0] for q in G2S[:2]]),
              L.encode_mont([q[1][1] for q in G2S[:2]]))
        f = jax.jit(DP.pairing)(px, py, (qx, qy))
        for i in range(2):
            got = T.decode_fp12(jax.tree.map(lambda a: a[i], f))
            assert got == HP.pairing(G1S[i], G2S[i])

    def test_product_check(self):
        px = L.encode_mont([p[0] for p in G1S[:2]])
        py = L.encode_mont([p[1] for p in G1S[:2]])
        negpy = L.encode_mont([C.G1.neg(p)[1] for p in G1S[:2]])
        qx = (L.encode_mont([q[0][0] for q in G2S[:2]]),
              L.encode_mont([q[0][1] for q in G2S[:2]]))
        qy = (L.encode_mont([q[1][0] for q in G2S[:2]]),
              L.encode_mont([q[1][1] for q in G2S[:2]]))
        ok = jax.jit(DP.pairing_product_is_one)(
            [(px, py), (px, negpy)], [(qx, qy), (qx, qy)])
        assert all(bool(v) for v in ok)
        bad = jax.jit(DP.pairing_product_is_one)(
            [(px, py), (px, py)], [(qx, qy), (qx, qy)])
        assert not any(bool(v) for v in bad)


def test_g1_recover_y_roundtrip():
    """Standalone G1 decompression API (kept alongside the fused
    g1_decompress_and_hash): wire x + sign -> point, vs host serialize."""
    import numpy as np
    from drand_tpu.crypto.host import serialize as S
    from drand_tpu.crypto.host.params import G1_GEN
    from drand_tpu.crypto.host import curve as HC
    from drand_tpu.ops import h2c as DH
    from drand_tpu.ops import limbs as L

    pts = [HC.G1.mul(G1_GEN, k) for k in (1, 7, 12345)]
    wires = [S.g1_to_bytes(p) for p in pts]
    xs = np.stack([np.asarray(L.int_to_limbs(p[0])) for p in pts])
    signs = jnp.asarray(np.array(
        [(w[0] >> 5) & 1 for w in wires], dtype=np.uint32))
    jac, ok = jax.jit(DH.g1_recover_y)(jnp.asarray(xs), signs)
    assert np.asarray(ok).all()
    assert DC.decode_g1_points(jac) == pts
