"""Batched threshold-partial verification (drand_tpu/crypto/partials.py)
against the host tbls golden path.  Shapes stay tiny (r=2, k=2) so each
orientation compiles one RLC and one exact program.
"""

import numpy as np
import pytest

from drand_tpu.crypto import partials, tbls
from drand_tpu.crypto.schemes import scheme_from_name


def _setup(scheme_id, t=2, n=3):
    sch = scheme_from_name(scheme_id)
    poly = tbls.PriPoly.random(t, secret=424243)
    shares = poly.shares(n)
    pp = poly.commit(sch.key_group)
    return sch, shares, pp, partials.BatchPartialVerifier(sch, pp, n)


@pytest.mark.parametrize("scheme_id", ["bls-unchained-on-g1",
                                       "pedersen-bls-unchained"])
def test_verify_partials_happy_and_fallback(scheme_id):
    sch, shares, pp, bv = _setup(scheme_id)
    msgs = [sch.digest_beacon(r, None) for r in (1, 2)]
    rows = [[tbls.sign_partial(sch, shares[i], m) for i in (0, 2)] for m in msgs]

    # happy path: RLC accepts everything the host accepts
    ok = bv.verify_partials(msgs, rows)
    assert ok.all()
    for m, row in zip(msgs, rows):
        for p in row:
            assert tbls.verify_partial(sch, pp, m, p)

    # corruption is localized by the exact fallback
    bad = bytearray(rows[1][0])
    bad[10] ^= 1
    rows2 = [rows[0], [bytes(bad), rows[1][1]]]
    assert bv.verify_partials(msgs, rows2).tolist() == [[True, True], [False, True]]
    assert not tbls.verify_partial(sch, pp, msgs[1], bytes(bad))

    # ragged rows pad with False; out-of-range signer index rejected
    forged = (5).to_bytes(2, "big") + rows[1][1][2:]
    rows3 = [[rows[0][0]], [forged, rows[1][1]]]
    assert bv.verify_partials(msgs, rows3).tolist() == [[True, False], [False, True]]

    # wrong-index partial (valid sig bytes under another share) fails
    swapped = rows[0][1][:2] + rows[0][0][2:]  # index 2 prefix, share-0 sig
    assert bv.verify_partials([msgs[0]], [[swapped]]).tolist() == [[False]]
    assert not tbls.verify_partial(sch, pp, msgs[0], swapped)


def test_verify_partials_empty():
    sch, shares, pp, bv = _setup("bls-unchained-on-g1")
    assert bv.verify_partials([], []).shape == (0, 0)
    assert bv.verify_partials([b"x"], [[]]).shape == (1, 0)


@pytest.mark.parametrize("scheme_id", ["bls-unchained-on-g1",
                                       "pedersen-bls-unchained"])
def test_verify_partials_non_decompressable_slot_localized(scheme_id):
    """ISSUE 10: the fast path decompresses ON DEVICE (the fused
    sqrt_ratio front end), so an x with no y on the curve is caught by
    the device parse_ok and localized by the exact fallback — matching
    the host golden decoder slot for slot."""
    sch, shares, pp, bv = _setup(scheme_id)
    msgs = [sch.digest_beacon(r, None) for r in (1, 2)]
    rows = [[tbls.sign_partial(sch, shares[i], m) for i in (0, 1)]
            for m in msgs]
    import drand_tpu.crypto.host.serialize as HS
    dec = HS.g2_from_bytes if sch.sig_group.point_len == 96 \
        else HS.g1_from_bytes
    found = False
    for tweak in range(1, 64):
        cand = bytearray(rows[0][1])
        cand[-1] ^= tweak                   # low x bits, index untouched
        try:
            dec(bytes(cand[2:]), check_subgroup=False)
        except (ValueError, AssertionError):
            found = True
            break
    assert found, "no non-decompressable tweak found"
    rows2 = [[rows[0][0], bytes(cand)], rows[1]]
    got = bv.verify_partials(msgs, rows2)
    assert got.tolist() == [[True, False], [True, True]]
    # host golden agrees the tweaked partial is invalid
    assert not tbls.verify_partial(sch, pp, msgs[0], bytes(cand))
