"""Committee-scale acceptance (ISSUE 13): the 1000-signer bars.

Marker `committee` (pytest.ini): the conftest gating auto-marks these
`slow` for tier-1 and they run when the file is named directly, under
``-m committee``, or with DRAND_TPU_RUN_HEAVY=1 — exactly like the
heavy-compile bucket.

  * the Handel overlay, driven in-process on a FakeClock timeline,
    produces the FULL verified aggregate for a 1024-signer round with
    every candidate window batch-verified through the verify service's
    LIVE lane (the service dispatch counter proves coalescing), with
    verdicts bit-identical to the flat fan-out path's verifier;
  * device DKG share verification for n=1024 completes in <= 4
    dispatches with accept/reject sets bit-identical to the host path,
    including the reshare constant-term check.
"""

import random

import pytest

from drand_tpu.beacon import handel as H
from drand_tpu.beacon.node import _host_verifier_factory
from drand_tpu.crypto import dkg_device as DD
from drand_tpu.crypto import tbls
from drand_tpu.crypto.host.params import R
from drand_tpu.crypto.schemes import scheme_from_name

pytestmark = pytest.mark.committee

N = 1024


def test_committee_1024_handel_full_aggregate_service_coalesced():
    from drand_tpu.crypto.verify_service import VerifyService

    scheme = scheme_from_name("pedersen-bls-chained")
    thr = 550
    rng = random.Random(1024)
    poly = tbls.PriPoly([rng.randrange(R) for _ in range(8)])
    # NOTE: the polynomial degree (8) is decoupled from the PROTOCOL
    # threshold (550) — recovery interpolates correctly from any >= 8
    # shares, while the session still demands 550 verified signers, so
    # the test keeps real crypto at committee scale without an
    # 550-coefficient host commit.
    pub = poly.commit(scheme.key_group)
    prev = b"\x42" * 32
    msg = scheme.digest_beacon(1, prev)
    partials = {i: tbls.sign_partial(scheme, poly.eval(i), msg)
                for i in range(N)}
    corrupt = sorted(rng.sample(range(1, N), 4))
    for c in corrupt:
        partials[c] = partials[c][:2] + partials[(c + 1) % N][2:]
    honest = [i for i in range(N) if i not in corrupt]

    svc = VerifyService()
    try:
        verifier = svc.partials_factory(_host_verifier_factory)(
            scheme, pub, N)     # submit_call -> LIVE lane
        completed = {}
        cfg = H.HandelConfig(min_group=2, fanout=4, window=64, bad_limit=3)
        sess = H.HandelSession(
            cfg, N, 0, thr, 1, prev, msg, verifier,
            send=lambda *a: None,
            on_complete=lambda parts: completed.update(parts))
        sess.add_own(partials[0])

        base = svc.stats()["dispatches"]
        levels = H.num_levels(N)
        candidates = 0
        ticks = 0
        # ideal-honest peers: each tick every level contributes a seeded
        # candidate covering the sender's whole side of the split
        while len(sess.verified) < len(honest) and ticks < 4 * levels:
            for level in range(1, levels + 1):
                block = H.level_block(N, 0, level)
                sender = block[rng.randrange(len(block))]
                side = H.own_block(N, sender, level)
                agg = H.Aggregate({i: partials[i] for i in side})
                sess.receive(level, sender, agg)
                candidates += 1
            sess.tick()
            ticks += 1

        # the FULL verified aggregate: every honest signer, no corrupt one
        assert set(sess.verified) == set(honest)
        assert len(completed) >= thr
        dispatches = svc.stats()["dispatches"] - base
        # coalescing: hundreds of candidates, at most one service
        # dispatch per tick window
        assert candidates >= 10 * ticks
        assert dispatches <= ticks + 1, (dispatches, ticks, candidates)

        # verdict parity with the flat fan-out path (same inner verifier
        # class, full set in one batch)
        from drand_tpu.beacon.chainstore import HostPartialVerifier
        flat = HostPartialVerifier(scheme, pub)
        all_bytes = list(partials.values())
        flat_verdicts = dict(zip(all_bytes, flat.verify(msg, all_bytes)))
        for p, ok in sess.checked.items():
            assert ok == flat_verdicts[p], "handel/flat verdict divergence"
        for c in corrupt:
            assert sess.checked[partials[c]] is False

        # the recovered signature is the group signature
        good = [sess.verified[i] for i in sorted(sess.verified)][:thr]
        sig = tbls.recover(scheme, pub, msg, good, thr, N,
                           verify_each=False)
        assert scheme.verify_beacon(
            scheme.key_group.to_bytes(pub.public_key()), 1, prev, sig)
    finally:
        svc.stop()


def test_committee_1024_device_dkg_dispatch_budget():
    """n=1024 share verification + reshare constant-term pin in <= 4
    dispatches, accept/reject sets bit-identical to the host loop."""
    if not DD.available():
        pytest.skip("jax unavailable")
    scheme = scheme_from_name("pedersen-bls-chained")
    g = scheme.key_group
    rng = random.Random(31337)
    t, holder = 4, 17
    polys = [tbls.PriPoly([rng.randrange(R) for _ in range(t)])
             for _ in range(N)]
    pubs = [p.commit(g) for p in polys]
    shares = [p.eval(holder).value for p in polys]
    wrong_share = sorted(rng.sample(range(N), 20))
    tampered = sorted(rng.sample(range(N), 20))
    for d in wrong_share:
        shares[d] = polys[d].eval(holder + 1).value
    for d in tampered:
        pubs[d].commits[rng.randrange(1, t)] = g.curve.mul(
            g.curve.gen, rng.randrange(R))

    before = DD.dispatch_count()
    dev = DD.verify_shares(g, [list(p.commits) for p in pubs],
                           holder, shares)
    # reshare constant-term check against a shared old polynomial: every
    # dealer whose C_{d,0} the old poly did not produce must be pinned
    old = tbls.PriPoly([rng.randrange(R) for _ in range(t)]).commit(g)
    claimed = [old.eval(d) for d in range(N)]
    mismatched = sorted(rng.sample(range(N), 10))
    for d in mismatched:
        claimed[d] = g.curve.mul(g.curve.gen, rng.randrange(R))
    ctm = DD.constant_terms_match(g, list(old.commits), range(N), claimed)
    used = DD.dispatch_count() - before
    assert used <= 4, f"{used} dispatches for n={N}"

    host = [g.curve.mul(g.curve.gen, s) == pubs[d].eval(holder)
            for d, s in enumerate(shares)]
    assert dev == host, "device/host accept-reject divergence"
    rejected = {d for d, ok in enumerate(dev) if not ok}
    assert set(wrong_share) <= rejected
    # a tampered NON-constant coefficient flips eval(holder) w.h.p.; the
    # exact verdict set is pinned by host parity above either way
    assert ctm == [d not in mismatched for d in range(N)]
