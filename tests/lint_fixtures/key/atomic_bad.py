"""Seeded atomic-write violations (checker: atomic).

Persistent group/share/journal writes under key/ must be temp+rename
(fs.write_atomic); every in-place truncate below is a finding, the
tempfile+os.replace and read-mode cases are negatives.
"""

import json
import os
import tempfile

from drand_tpu import fs


def save_group_torn(path, group):
    # VIOLATION: open-for-write truncates in place; a crash mid-write
    # leaves an unparseable TOML exactly where load_group looks
    with open(path, "w") as f:
        f.write(group.to_toml())


def save_share_torn(path, data: bytes):
    # VIOLATION: os.open with O_CREAT|O_TRUNC is the same in-place
    # truncate with extra steps
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        os.write(fd, data)
    finally:
        os.close(fd)


def save_journal_appended(path, record):
    # VIOLATION: append mode still mutates the live file in place
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")


def save_group_atomic(path, group):
    # negative: spells out the discipline itself — temp sibling + rename
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    with os.fdopen(fd, "w") as f:
        f.write(group.to_toml())
    os.replace(tmp, path)


def save_share_atomic(path, data: bytes):
    # negative: routes through the sanctioned helper
    fs.write_atomic(path, data, secure=True)


def load_group(path):
    # negative: read-mode open is not a write
    with open(path) as f:
        return f.read()


def save_lockfile_inplace(path):
    # justified in-place write: a lockfile's CONTENT is meaningless,
    # only its existence matters — torn bytes are fine
    with open(path, "w") as f:  # tpu-vet: disable=atomic — existence-only file
        f.write("locked")
