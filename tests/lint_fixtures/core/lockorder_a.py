"""Cross-module lock-discipline half A (tests/test_vet.py fixture).

Alone, this module is clean to the per-class v2 pass: `PlacerA` never
nests its own locks, never blocks directly, and never writes `plan`
bare.  Every seeded bug here needs the OTHER module's summaries:

  * `refresh` holds `PlacerA._lock` and calls `RegistryB.snapshot`,
    which takes `RegistryB._lock`; `RegistryB.rebalance` does the
    reverse — a two-class lock-order cycle only the project-wide graph
    sees.
  * `enqueue` launders its guarded `self.plan` mutation through
    `append_entry` in lockorder_b (lock-helper-mutation).
  * `drain_slow` holds the lock across `slow_sync`, which sleeps one
    frame down (lock-blocking-transitive).
"""

import threading
import time

from core.lockorder_b import RegistryB, append_entry


def slow_sync():
    time.sleep(0.5)


class PlacerA:
    def __init__(self):
        self._lock = threading.Lock()
        self.plan = []
        self._reg = RegistryB()

    def place(self, item):
        with self._lock:
            self.plan.append(item)

    def refresh(self):
        # BAD (v3 only): holds PlacerA._lock, snapshot() takes
        # RegistryB._lock — half of the cross-module cycle
        with self._lock:
            return self._reg.snapshot()

    def enqueue(self, item):
        # BAD (v3 only): append_entry mutates self.plan one frame down,
        # and no lock is held here (lock-helper-mutation)
        append_entry(self.plan, item)

    def enqueue_locked(self, item):
        with self._lock:
            append_entry(self.plan, item)   # fine: guarding lock held

    def drain_slow(self):
        # BAD (v3 only): slow_sync() sleeps while PlacerA._lock is held
        # (lock-blocking-transitive)
        with self._lock:
            slow_sync()
