"""Cross-function wall-clock reads (clock-interproc-call): caught by v2,
missed by the v1 per-function pass."""

from core.clock_util import boot_label, wall_now


def deadline_for_round(period):
    # BAD (v2 only): wall_now() launders time.time() through a helper in
    # another module — chaos determinism breaks just the same
    return wall_now() + period


def tag():
    # OK: not a wall-clock value
    return boot_label()
