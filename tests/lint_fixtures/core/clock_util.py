"""Wall-clock laundering helper — the clock checker's cross-function
pair's helper half (tests/test_vet.py).

The direct read below is deliberately suppressed: the POINT of this
fixture is the return value.  Phase 1 marks `wall_now()`
``returns_wallclock``, so v2 flags its *callers* (core/clock_flow_bad.py)
while the v1 per-function pass sees only this suppressed line."""

import time


def wall_now():
    return time.time()  # tpu-vet: disable=clock


def boot_label():
    return "boot"
