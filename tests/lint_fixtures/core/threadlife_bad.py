"""Seeded thread-lifecycle violations: anonymous/unregistered names,
join-less owners, fire-and-forget orphans — the PR 7/8/12 leak class."""

import threading


def make_pump(fn):
    """OK: constructs but does not start — ownership (and the phase-1
    ``returns_thread`` summary) transfers to the caller."""
    return threading.Thread(target=fn, daemon=True, name="relay-pump")


def start_made_pump(fn):
    # BAD (v2 only): make_pump() hands back a thread (returns_thread);
    # starting and dropping it is the same leak as constructing it here,
    # but v1 sees an opaque call and misses it (threadlife-orphan)
    t = make_pump(fn)
    t.start()


class LeakyOwner:
    """BAD x2: `_pump` has no join anywhere; `_probe` is joined only
    from a method stop() never reaches (threadlife-no-join)."""

    def start(self, fn):
        self._pump = threading.Thread(target=fn, daemon=True,
                                      name="relay-pump")
        self._pump.start()
        self._probe = threading.Thread(target=fn, daemon=True,
                                       name="probe-net")
        self._probe.start()

    def _reap_probe(self):
        self._probe.join(timeout=2)

    def stop(self):
        self._pump = None          # dropped, never joined


class NoStopOwner:
    """BAD: owns a thread but has no stop()/close()/shutdown() at all."""

    def launch(self, fn):
        self._ticker = threading.Thread(target=fn, daemon=True,
                                        name="ticker")
        self._ticker.start()


class CleanOwner:
    """OK: the tuple-swap + bounded-join idiom the codebase uses."""

    def launch(self, fn):
        self._pump = threading.Thread(target=fn, daemon=True,
                                      name="relay-pump")
        self._pump.start()

    def close(self):
        t, self._pump = self._pump, None
        if t is not None:
            t.join(timeout=2)


def fire_and_forget(fn):
    # BAD: unbound start — nothing can stop or await it
    # (threadlife-orphan)
    threading.Thread(target=fn, daemon=True, name="relay-oneshot").start()


def local_leak(fn):
    # BAD: started and dropped (threadlife-orphan)
    t = threading.Thread(target=fn, daemon=True, name="relay-drop")
    t.start()


def local_joined(fn):
    # OK: bounded-join before returning
    t = threading.Thread(target=fn, daemon=True, name="relay-scoped")
    t.start()
    t.join(timeout=3)


def handed_off(fn, registry):
    # OK: ownership handed to the registry
    t = threading.Thread(target=fn, daemon=True, name="relay-handoff")
    t.start()
    registry.adopt(t)


def bad_names(fn):
    # BAD: anonymous (threadlife-unnamed)
    t = threading.Thread(target=fn, daemon=True)
    # BAD: unregistered prefix (threadlife-unregistered-name)
    u = threading.Thread(target=fn, daemon=True, name="mystery-pump")
    # BAD: fully dynamic name — no static prefix for the registry
    v = threading.Thread(target=fn, daemon=True, name=fn.__name__)
    for w in (t, u, v):
        w.start()
    for w in (t, u, v):
        w.join(timeout=1)


def justified_oneshot(fn):
    # the interpreter-exit path cannot join across teardown, justified:
    # tpu-vet: disable=threadlife
    threading.Thread(target=fn, daemon=True, name="stop-oneshot").start()
