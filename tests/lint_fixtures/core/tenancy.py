"""Seeded bounds-checker violation for the tenancy scope (rel path
`core/tenancy.py` — the registry joined the serving-path scope in ISSUE
15: it sits on every admission decision, so queues/executors grown there
are flood-reachable).

One BAD line must be caught; the OK lines must stay silent."""

import queue
from concurrent.futures import ThreadPoolExecutor


def registry_event_fanout():
    events = queue.Queue()                 # BAD: unbounded on the registry
    return events


def bounded_fanout():
    events = queue.Queue(maxsize=64)       # OK: bounded
    pool = ThreadPoolExecutor(max_workers=2)   # OK: bounded
    return events, pool


def audit_log_spool():
    # justified: drained synchronously under the registry lock before the
    # next Control-plane edit returns; never request-reachable
    spool = queue.Queue()   # tpu-vet: disable=bounds
    return spool
