"""Cross-module lock-discipline half B (tests/test_vet.py fixture).

`RegistryB.rebalance` holds `RegistryB._lock` and calls back into
`PlacerA.place` (which takes `PlacerA._lock`) — the closing edge of the
two-class cycle seeded in lockorder_a.  `Notifier` is the PR 15
listener-under-lock shape: callbacks registered via `subscribe` are
invoked while `Notifier._lock` is held, so a registered callback that
sleeps is a stall the callback-registration rule must catch.

Fixture modules are parsed, never imported — the circular import with
lockorder_a is deliberate and harmless.
"""

import threading
import time

from core.lockorder_a import PlacerA


def append_entry(plan, item):
    plan.append(item)


class RegistryB:
    def __init__(self):
        self._lock = threading.Lock()
        self.rows = {}
        self._placer = PlacerA()

    def snapshot(self):
        with self._lock:
            return dict(self.rows)

    def rebalance(self, item):
        # BAD (v3 only): holds RegistryB._lock, place() takes
        # PlacerA._lock — the cycle's closing edge
        with self._lock:
            self._placer.place(item)


class Notifier:
    def __init__(self):
        self._lock = threading.Lock()
        self._subs = []

    def subscribe(self, cb):
        with self._lock:
            self._subs.append(cb)

    def fire(self, value):
        with self._lock:
            for cb in self._subs:
                cb(value)


class ListenerA:
    def __init__(self):
        self._notifier = Notifier()
        # BAD (v3 only): on_event sleeps, and Notifier.fire invokes it
        # while holding Notifier._lock (lock-callback-blocking)
        self._notifier.subscribe(self.on_event)

    def on_event(self, value):
        time.sleep(0.1)
