"""Seeded fleet-harness deadline violations: the file is named fleet.py,
so the deadline checker's test-code exemption does NOT apply — a wedged
fleet run must die in minutes, not hang CI."""

import socket
import subprocess


def reap(proc):
    # BAD: Popen.wait() with no timeout — a wedged daemon hangs the
    # supervisor forever (deadline-unbounded-call)
    return proc.wait()


def spawn(cmd):
    # BAD: no timeout on the subprocess run
    return subprocess.run(cmd, capture_output=True)


class BadProxy:
    """Accept loop with no settimeout discipline anywhere in the class:
    a silent peer parks the accept thread forever."""

    def __init__(self, listener):
        self.listener = listener

    def serve(self):
        while True:
            conn, _ = self.listener.accept()     # BAD
            data = conn.recv(4096)               # BAD
            conn.sendall(data)


class GoodProxy:
    """The poll-slice discipline: settimeout in scope bounds every
    accept/recv to one slice."""

    def __init__(self, listener):
        self.listener = listener
        self.listener.settimeout(0.25)

    def serve(self):
        while True:
            try:
                conn, _ = self.listener.accept()     # OK: bounded
            except socket.timeout:
                continue
            conn.settimeout(0.25)
            conn.recv(4096)                          # OK: bounded


def reap_bounded(proc, budget):
    # OK: the supervisor's budget reaches the wait
    return proc.wait(timeout=budget)
