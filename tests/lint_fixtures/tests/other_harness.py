"""Ordinary test-support code: same shapes as fleet.py, but the file is
NOT the fleet harness, so the deadline checker's test exemption applies
(pytest owns the watchdog here) and nothing fires."""

import subprocess


def reap(proc):
    return proc.wait()          # exempt: test code


def spawn(cmd):
    return subprocess.run(cmd, capture_output=True)     # exempt


class Echo:
    def __init__(self, listener):
        self.listener = listener

    def serve(self):
        conn, _ = self.listener.accept()    # exempt
        conn.sendall(conn.recv(4096))
