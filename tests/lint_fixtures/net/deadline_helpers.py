"""Deadline-threading helpers for the cross-function fixture pair.

`rpc` REQUIRES its timeout: the parameter defaults to None and flows
bare into `urlopen` — callers that omit it run unbounded
(``required_deadline`` summary).  `rpc_defaulted` self-bounds with the
``timeout or DEFAULT`` idiom (net/client.py style) and never burdens
callers."""

from urllib.request import urlopen

DEFAULT_TIMEOUT = 5.0


def rpc(url, timeout=None):
    return urlopen(url, timeout=timeout)


def rpc_defaulted(url, timeout=None):
    return urlopen(url, timeout=timeout or DEFAULT_TIMEOUT)
