"""Seeded bounds-checker violations (scope: rel path starts with net/).

Each BAD line must be caught; each OK line must stay silent."""

import queue
from concurrent.futures import ThreadPoolExecutor
from http.server import HTTPServer, ThreadingHTTPServer
from queue import Queue


def unbounded_queues():
    a = queue.Queue()                      # BAD: no maxsize
    b = Queue()                            # BAD: from-import alias
    c = queue.Queue(maxsize=0)             # BAD: 0 spells unbounded
    d = queue.LifoQueue()                  # BAD: sibling class
    e = queue.SimpleQueue()                # BAD: cannot be bounded
    return a, b, c, d, e


def bounded_queues():
    a = queue.Queue(maxsize=64)            # OK: kw bound
    b = queue.Queue(8)                     # OK: positional bound
    n = 16
    c = Queue(maxsize=n)                   # OK: computed bound exists
    return a, b, c


def executors():
    bad = ThreadPoolExecutor()             # BAD: machine-sized pool
    good = ThreadPoolExecutor(max_workers=4)   # OK
    also = ThreadPoolExecutor(4)           # OK: positional
    return bad, good, also


def servers():
    bad = ThreadingHTTPServer(("", 0), None)   # BAD: thread per request
    good = HTTPServer(("", 0), None)           # OK: no thread growth
    return bad, good


class BadServer(ThreadingHTTPServer):      # BAD: subclass inherits the bug
    pass


def justified():
    # tpu-vet: disable=bounds  (drained by a fixed reaper; depth metered)
    return queue.Queue()
