"""Seeded deadline violations: unbounded blocking primitives and an
unthreaded budget — the r06 hung-probe class."""

import socket
import subprocess
from urllib.request import urlopen

from net.deadline_helpers import rpc, rpc_defaulted


def probe(cmd):
    # BAD: no timeout — a hung probe holds this thread forever
    # (deadline-unbounded-call)
    return subprocess.run(cmd, capture_output=True)


def fetch_status(url):
    # BAD: explicit timeout=None counts as absent
    return urlopen(url, timeout=None)


def drain(proc):
    # BAD: communicate() with no timeout
    out, _ = proc.communicate()
    return out


def call_without_budget(url):
    # BAD: rpc() passes `timeout` straight into urlopen with no
    # fallback — omitting it runs unbounded (deadline-not-threaded)
    return rpc(url)


def connect(addr):
    # OK: bounded
    return socket.create_connection(addr, 5.0)


def probe_bounded(cmd, budget):
    # OK: bounded by the caller's budget
    return subprocess.run(cmd, timeout=budget, capture_output=True)


def call_with_budget(url, budget):
    # OK: budget threaded through to the blocking call
    return rpc(url, timeout=budget)


def call_defaulted(url):
    # OK: the callee self-bounds (`timeout or DEFAULT_TIMEOUT`)
    return rpc_defaulted(url)


def justified_wait(proc):
    return proc.communicate()  # tpu-vet: disable=deadline
