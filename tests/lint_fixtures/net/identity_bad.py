"""Seeded identity-plane secret leaks (tests/test_vet.py fixture).

Token root keys (core/authz.py) and TLS private keys (net/identity.py)
are bearer-grade material: a leaked root key mints arbitrary tenant
tokens, a leaked node key impersonates the node to the whole committee.
The `secret` checker must treat them exactly like DKG shares — no log,
no exception message, no __repr__, no print.
"""


def hash_secret(value):
    return b"sanitized"


class TokenAuthorityish:
    def __init__(self, root_key, log):
        self._root_key = root_key
        self.log = log

    def leak_root_key(self):
        self.log.info("authority up", root_key=self._root_key)  # VIOLATION

    def leak_exception(self):
        raise RuntimeError(
            f"ledger torn, key was {self._root_key}")           # VIOLATION

    def __repr__(self):
        return f"TokenAuthority(key={self._root_key})"          # VIOLATION

    def safe_token_id(self, token_id):
        # token ids are public handles, not key material: fine
        self.log.info("minted", token_id=token_id)

    def safe_proof(self):
        proof = hash_secret(self._root_key)                     # sanitizer
        self.log.info("rotated", proof=proof)


class CertGenerationish:
    def __init__(self, key_pem, cert_pem, log):
        self.key_pem = key_pem
        self.cert_pem = cert_pem
        self.log = log

    def leak_tls_key(self):
        print("loaded node key", self.key_pem)                  # VIOLATION

    def leak_one_hop(self):
        pem = self.key_pem
        self.log.debug("reload", material=pem)                  # VIOLATION

    def safe_public_half(self):
        # the CERTIFICATE is what the wire already shows every peer,
        # and len() of the key is a sanitized size: both fine
        self.log.info("reload ok", cert=self.cert_pem,
                      key_bytes=len(self.key_pem))

    def suppressed(self):
        # tpu-vet: disable=secret
        self.log.debug("dump", key_pem=self.key_pem)
