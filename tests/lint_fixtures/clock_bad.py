"""Seeded clock-discipline violations (tests/test_vet.py fixture)."""

import time
import time as _t
from time import monotonic, sleep

CLOCK_PERIOD = 30


def direct_time():
    return time.time()                  # VIOLATION: clock-direct-call


def aliased_monotonic():
    return _t.monotonic()               # VIOLATION: resolved through alias


def from_import_sleep():
    sleep(0.1)                          # VIOLATION: from-import
    return monotonic()                  # VIOLATION: from-import


def allowed_measurement():
    # perf_counter is latency measurement, not schedule logic: NOT flagged
    t0 = time.perf_counter()
    return time.perf_counter() - t0


def suppressed_same_line():
    return time.time()  # tpu-vet: disable=clock


def suppressed_line_above():
    # tpu-vet: disable=clock
    return time.sleep(0.0)
