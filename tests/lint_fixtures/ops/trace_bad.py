"""Seeded trace-safety violations (tests/test_vet.py fixture).

The `jax` import here is a decoy name — the analyzer only parses, so no
real JAX is needed (and none is imported by the vet run)."""

import functools

import jax
import jax.numpy as jnp

_trace_log = []


def make_accumulator():
    seen = []

    @jax.jit
    def accumulate(x):
        seen.append(x)                  # VIOLATION: captured mutation
        return jnp.sum(x)

    return accumulate


@jax.jit
def branch_on_tracer(x):
    if x > 0:                           # VIOLATION: python branch on tracer
        return x
    return -x


@jax.jit
def concretize(x):
    n = int(x)                          # VIOLATION: int() on tracer
    return x.item() + n                 # VIOLATION: .item() on tracer


@jax.jit
def loop_on_tracer(x, ys):
    total = x
    for y in ys:                        # VIOLATION: python loop over tracer
        total = total + y
    return total


@functools.partial(jax.jit, static_argnums=(1,))
def static_is_fine(x, n):
    # n is static_argnums: branching on it is fine
    if n > 4:
        return jnp.zeros((n,))
    acc = x
    for _ in range(n):                  # range(static) is fine
        acc = acc * 2
    return acc


@jax.jit
def shapes_are_static(x):
    # shape/ndim/dtype/len derive static values: none of this is flagged
    if x.ndim > 1:
        return x.reshape(-1)
    half = x.shape[0] // 2
    if half > 0:
        return x[:half]
    return x


def host_side(x):
    # not jitted: python control flow is the point here
    if x > 0:
        return [int(x)]
    return []


@jax.jit
def suppressed(x):
    # tpu-vet: disable=trace
    if x > 0:
        return x
    return -x
