"""Seeded violations for the trace checker's host-hash-in-loop rule
(ISSUE 14): per-lane host hashing inside a loop on a hot-path module is
O(n) GIL-bound pack work per chunk — the stage the device hash-to-field
front removed.  Every BAD line must be caught; negatives stay silent."""

import hashlib
from hashlib import sha256

import numpy as np


def digest_loop(msgs):
    out = []
    for m in msgs:
        out.append(hashlib.sha256(m).digest())      # BAD: hashlib in loop
    return out


def aliased_digest_while(msgs):
    out = []
    while msgs:
        out.append(sha256(msgs.pop()).digest())     # BAD: aliased hashlib
    return out


def helper_loop(msgs, dst):
    from drand_tpu.crypto.host.h2c import hash_to_field_fp
    return [hash_to_field_fp(m, dst, 2) for m in msgs]  # BAD: h2f helper


def scheme_digest_comprehension(scheme, rounds):
    return [scheme.digest_beacon(r, None) for r in rounds]  # BAD: per lane


def hash_once_outside_loop(msgs):
    """Negative: one digest over the joined batch is not per-lane work."""
    joined = hashlib.sha256(b"".join(msgs)).digest()
    out = []
    for m in msgs:
        out.append(len(m))                          # host metadata: fine
    return joined, out


def numpy_pack_loop(msgs):
    """Negative: numpy word packing per message is the sanctioned pack
    stage — no hashing involved."""
    return [np.frombuffer(m, np.uint8) for m in msgs]


def justified_oracle(msgs, scheme):
    """A justified per-lane digest (the parity oracle) suppresses."""
    out = []
    for r in msgs:
        # tpu-vet: disable=trace  (parity oracle fixture)
        out.append(scheme.digest_beacon(r, None))
    return out
