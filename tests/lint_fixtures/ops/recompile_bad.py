"""Seeded recompile violations at call sites — the jitted defs live in
crypto/recompile_kernels.py, so every dispatch-hygiene code here needs
the cross-module phase-1 summaries."""

from jax.sharding import Mesh

from crypto.recompile_kernels import make_hasher, pack_lanes, tile


def dispatch(x, counts, cfg):
    # BAD: .item() into a static slot — every distinct value is a fresh
    # program flavor (recompile-data-dependent-static)
    y = pack_lanes(x, counts.item())
    # BAD: int() of runtime data into the same static slot
    y = pack_lanes(y, int(counts))
    # OK: shape-derived flavor constants are the sanctioned selector
    y = pack_lanes(y, int(x.shape[0]))
    # OK: config-derived flavor constant
    return pack_lanes(y, cfg.lanes)


def bad_static_display(x):
    # BAD: unhashable list display in a static slot
    # (recompile-unhashable-static)
    return tile(x, dims=[4, 4])


def bad_factory(x, n):
    # BAD: data-dependent scalar into a jit factory
    # (recompile-data-dependent-flavor)
    return make_hasher(n.item())(x)


def fresh_mesh(devices):
    # BAD: placement object minted outside crypto/device_pool.py
    # (recompile-per-call-placement)
    return Mesh(devices, ("lanes",))


def justified_mesh(devices):
    # one-off diagnostic mesh in an operator path, justified:
    return Mesh(devices, ("lanes",))  # tpu-vet: disable=recompile
