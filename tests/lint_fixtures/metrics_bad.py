"""Seeded metric-label cardinality violations: a peer address, a round
number, or a request URL as a label value is one Prometheus time series
per distinct value."""

from drand_tpu.metrics import registered_label

STATE_NAMES = {0: "open", 1: "closed"}


def record(m, peer_addr, beacon_id, round_no, state):
    # BAD: a peer address is one time series per peer
    # (metriclabel-unbounded)
    m.labels(peer_addr).inc()
    # BAD: a round number is unbounded by construction
    m.labels(f"round-{round_no}").inc()
    # OK: bounded identifier
    m.labels(beacon_id).inc()
    # OK: literal
    m.labels("aggregate").inc()
    # OK: the sanctioned sanitizer caps the registry
    m.labels(registered_label(peer_addr, ns="peer-address")).inc()
    # OK: lookup into a bounded table
    m.labels(STATE_NAMES[state]).inc()


def record_attr(m, req):
    # BAD: attribute with an unbounded terminal
    m.labels(req.url).observe(1.0)
    # OK: bounded terminal through an attribute
    m.labels(req.route).observe(1.0)


def record_local(m, cfg, addr):
    # OK: a local assigned from a bounded expression (one hop)
    lane_value = cfg.lane
    m.labels(lane_value).set(1)
    # suppressed: justified one-off debug metric
    m.labels(addr).set(1)  # tpu-vet: disable=metriclabel
