"""Seeded violations for the `wait` checker: unbounded blocking waits.

Four findings (future.result / thread.join / condition.wait /
event.wait, all zero-argument), one suppressed, and negatives that must
stay silent: bounded variants, str.join (always has an argument), and a
non-blocking queue get.
"""

import queue
import threading
from concurrent.futures import Future


def bad_future(f: Future):
    return f.result()                           # finding: wait-unbounded


def bad_join(t: threading.Thread):
    t.join()                                    # finding: wait-unbounded


class Waiter:
    def __init__(self):
        self.cond = threading.Condition()
        self.ev = threading.Event()

    def bad_cond_wait(self):
        with self.cond:
            self.cond.wait()                    # finding: wait-unbounded

    def bad_event_wait(self):
        self.ev.wait()                          # finding: wait-unbounded


def ok_bounded(f: Future, t: threading.Thread, w: Waiter):
    f.result(5)
    f.result(timeout=5)
    t.join(timeout=2)
    with w.cond:
        w.cond.wait(0.1)
    w.ev.wait(timeout=1.0)


def ok_str_join(parts):
    return ", ".join(parts)


def ok_queue_nonblocking(q: queue.Queue):
    return q.get_nowait()


def ok_suppressed(f: Future):
    # the supervising test harness guarantees resolution here
    return f.result()  # tpu-vet: disable=wait
