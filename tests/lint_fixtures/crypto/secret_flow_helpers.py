"""Interprocedural secret-flow helpers — the cross-function half of the
v1-miss/v2-catch pair (tests/test_vet.py).

`current_material` launders `vault.get_share()` through a return value;
nothing at its call sites looks secret-ish to a per-function pass.  The
phase-1 summary marks it ``returns_secret``.  `report_material` logs its
`material` parameter, so a secret bound there leaks one frame down
(``logged_params`` summary)."""


def current_material(vault):
    return vault.get_share()


def report_material(log, material):
    log.info("dkg material state: %s", material)
