"""Jitted kernels + a jit factory for the recompile fixture pair.

The static-arg summaries harvested here (phase 1) drive the call-site
checks in ops/recompile_bad.py — the `@jit(static_...)` def and the bad
call sites live in different modules on purpose: that is the exact
cross-function shape of the PR 11 watchdog-floor incident."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnums=(1,))
def pack_lanes(x, lanes):
    return x.reshape((lanes, -1))


@partial(jax.jit, static_argnames=("pad",))
def pad_block(x, pad=4):
    return jnp.pad(x, pad)


# BAD: unhashable default on a static param — jit hashes static args
@partial(jax.jit, static_argnames=("dims",))
def tile(x, dims=[8, 128]):
    return jnp.tile(x, dims)


def make_hasher(width):
    """jit factory: each call builds a fresh program flavor."""
    return jax.jit(lambda m: m % width)
