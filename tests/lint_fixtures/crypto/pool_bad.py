"""Scoping case for the device-enumeration rule: crypto/ exempts raw
BatchBeaconVerifier construction, but enumeration is allowed ONLY in
crypto/device_pool.py — this sibling module must still be flagged."""

import jax

from drand_tpu.crypto.batch import BatchBeaconVerifier


def construction_is_fine_here(scheme, pk):
    return BatchBeaconVerifier(scheme, pk)          # allowed: crypto/


def enumeration_is_not():
    return jax.devices()                            # VIOLATION: not the pool
