"""Negative case for the verifier checker: this file's rel starts with
crypto/, the package that owns the device pipelines — direct
construction here is the sanctioned internal path and must NOT be
flagged."""

from drand_tpu.crypto.batch import BatchBeaconVerifier


def service_internal_construction(scheme, pk):
    return BatchBeaconVerifier(scheme, pk, pad_to=8192)     # allowed
