"""Cross-function secret leaks: caught by the v2 interprocedural engine,
invisible to the v1 per-function pass (regression-tested both ways in
tests/test_vet.py)."""

from crypto.secret_flow_helpers import current_material, report_material


def leak_via_source(log, vault):
    # BAD (v2 only): current_material() returns vault.get_share() — the
    # helper launders the secret through a return value (secret-in-log)
    log.info("material=%s", current_material(vault))


def leak_via_sink(log, vault):
    # BAD (v2 only): report_material() logs its `material` parameter —
    # the leak is one frame down, the bug is here (secret-interproc-log)
    report_material(log, vault.get_share())


def hashed_is_fine(log, vault):
    # OK: sanitized before crossing the call boundary
    report_material(log, hash_secret(current_material(vault)))
