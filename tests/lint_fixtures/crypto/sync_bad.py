"""Seeded violations for the trace checker's sync-in-loop rule: device
readback inside a per-chunk loop serializes the stream (one interconnect
round trip per iteration).  Every BAD line must be caught; the negatives
must stay silent."""

import jax
import numpy as np


def _rlc_pipeline():
    return lambda chunk: chunk


def per_chunk_sync_loop(chunks, backend):
    pipe = _rlc_pipeline()
    out = []
    for c in chunks:
        verdict = pipe(c)
        if bool(verdict):                       # BAD: sync per chunk
            out.append(np.asarray(verdict))     # BAD: readback per chunk
        jax.block_until_ready(verdict)          # BAD: explicit sync
    return out


def per_chunk_dispatch_loop(chunks, backend):
    totals = []
    while chunks:
        d = backend.dispatch_packed(chunks.pop())
        totals.append(float(d))                 # BAD: concretize per chunk
        d.block_until_ready()                   # BAD: method sync per chunk
    return totals


def sync_once_after_stream(chunks, backend):
    """Negative: ONE sync point after the loop is the async pattern."""
    inflight = []
    for c in chunks:
        inflight.append(backend.dispatch_packed(c))
    last = inflight[-1]
    return bool(last)                           # outside the loop: fine


def host_work_in_loop(chunks):
    """Negative: host-side numpy in a loop is not a device sync."""
    metas = []
    for c in chunks:
        n = len(c)
        metas.append(np.asarray([n]))           # host data: fine
    return metas


def jitted_inner_is_not_host_code(chunks):
    """Negative: a loop inside a nested JITTED function is traced device
    code (compile-time), not a per-chunk host loop."""
    def run(xs):
        for x in xs:
            jax.block_until_ready(x)
        return xs
    return jax.jit(run)


def outer_with_nested_host_loop(backend, chunks):
    """A nested HOST function's loop is flagged exactly once, attributed
    to the inner function."""
    def inner():
        while chunks:
            d = backend.dispatch_packed(chunks.pop())
            jax.block_until_ready(d)            # BAD: once, in inner()
    return inner


def justified_bisection(chunks, backend):
    """A justified per-chunk readback (failure localization) suppresses."""
    for c in chunks:
        v = backend.dispatch_packed(c)
        if bool(v):  # tpu-vet: disable=trace  (bisection localizes per chunk)
            return c
    return None
