"""Negative case for the verifier checker's device-enumeration rule:
this file's rel is crypto/device_pool.py — the ONE module that owns
device inventory — so raw enumeration here is the sanctioned call site
and must NOT be flagged."""

import jax


def sanctioned_enumeration():
    return jax.devices()                            # allowed (the pool)


def sanctioned_local_enumeration():
    return jax.local_devices()                      # allowed (the pool)
