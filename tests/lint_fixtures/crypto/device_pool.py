"""Negative case for the verifier checker's device-enumeration rule:
this file's rel is crypto/device_pool.py — the ONE module that owns
device inventory — so raw enumeration here is the sanctioned call site
and must NOT be flagged."""

import jax


def sanctioned_enumeration():
    return jax.devices()                            # allowed (the pool)


def sanctioned_local_enumeration():
    return jax.local_devices()                      # allowed (the pool)


def sanctioned_mesh(devices):
    from jax.sharding import Mesh
    return Mesh(devices, ("lanes",))                # allowed (the home)


def churny_mesh(device_lists):
    from jax.sharding import Mesh
    out = []
    for devs in device_lists:
        # BAD even at home: a placement object per loop iteration
        # (recompile-per-call-placement)
        out.append(Mesh(devs, ("lanes",)))
    return out
