"""Seeded secret-hygiene violations (tests/test_vet.py fixture)."""


def hash_secret(value):
    return b"sanitized"


class Vaultish:
    def __init__(self, share, log):
        self._share = share
        self.log = log

    def leak_to_log(self):
        self.log.info("dkg state", share=self._share)   # VIOLATION

    def leak_one_hop(self):
        s = self._share
        self.log.debug("state", dump=s)                 # VIOLATION: taint hop

    def leak_exception(self, secret):
        raise ValueError(f"bad secret {secret}")        # VIOLATION

    def __repr__(self):
        return f"Vaultish({self._share})"               # VIOLATION

    def safe_hash(self, secret):
        proof = hash_secret(secret)                     # sanitizer: fine
        self.log.info("joining", proof=proof)

    def safe_literal(self):
        # string literals mentioning secrets are not values: fine
        self.log.warn("need --secret-file or DRAND_SHARE_SECRET")
        raise SystemExit("wrong setup secret")

    def suppressed(self):
        # tpu-vet: disable=secret
        self.log.debug("debug dump", share=self._share)
