"""Seeded lock-discipline violations (tests/test_vet.py fixture)."""

import queue
import threading


class UnguardedWrite:
    """self.count is guarded in incr() but mutated bare in reset()."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.items = []

    def incr(self):
        with self._lock:
            self.count += 1
            self.items.append(self.count)

    def reset(self):
        self.count = 0                  # VIOLATION: lock-unguarded-write
        self.items.clear()              # VIOLATION: mutator without lock

    def reset_locked(self):
        with self._lock:
            self.count = 0              # fine: lock held

    def reset_suppressed(self):
        # callers of this helper hold self._lock
        # tpu-vet: disable=lock
        self.count = 0


class BlockingUnderLock:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._ev = threading.Event()
        self._cond = threading.Condition()
        self.state = 0

    def drain(self):
        with self._lock:
            self.state = 1
            return self._q.get(timeout=5)   # VIOLATION: blocking Queue.get

    def pause(self):
        with self._lock:
            self.state = 2
            self._ev.wait(1.0)          # VIOLATION: Event.wait keeps the lock

    def fast_path(self):
        with self._lock:
            self.state = 3
            return self._q.get_nowait()     # fine: non-blocking

    def nonblocking(self):
        with self._lock:
            self.state = 4
            return self._q.get(block=False)  # fine: block=False

    def cv_wait(self):
        with self._cond:
            self._cond.wait(0.1)        # fine: Condition.wait releases it


class OrderAB:
    """Acquires a then b in one method, b then a in another: cycle."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.x = 0

    def forward(self):
        with self._a:
            with self._b:               # VIOLATION edge a->b
                self.x = 1

    def backward(self):
        with self._b:
            with self._a:               # VIOLATION edge b->a: cycle
                self.x = 2


class SelfDeadlock:
    def __init__(self):
        self._lock = threading.Lock()   # NON-reentrant
        self.n = 0

    def outer(self):
        with self._lock:
            self.inner()                # VIOLATION: re-acquires self._lock

    def inner(self):
        with self._lock:
            self.n += 1


class ReentrantOk:
    def __init__(self):
        self._lock = threading.RLock()  # reentrant: NOT flagged
        self.n = 0

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            self.n += 1
