"""Seeded verifier-discipline violations (tests/test_vet.py fixture).

This file's rel has no crypto/ prefix, so every direct
BatchBeaconVerifier construction below must be flagged; the crypto/
sibling fixture (crypto/verifier_ok.py) proves the exemption."""

from drand_tpu.crypto.batch import BatchBeaconVerifier
from drand_tpu.crypto import batch
from drand_tpu.crypto.batch import BatchBeaconVerifier as BBV


def direct_construction(scheme, pk):
    return BatchBeaconVerifier(scheme, pk)          # VIOLATION


def module_attr_construction(scheme, pk):
    return batch.BatchBeaconVerifier(scheme, pk, pad_to=8192)   # VIOLATION


def aliased_construction(scheme, pk):
    return BBV(scheme, pk)                          # VIOLATION: alias


def service_route_is_fine(scheme, pk):
    # the sanctioned path: NOT flagged
    from drand_tpu.crypto.verify_service import get_service
    return get_service().handle(scheme, pk)


def host_fallback_is_fine(scheme, pk):
    # HostBatchVerifier is the jax-free fallback, not the device pipeline:
    # NOT flagged
    from drand_tpu.crypto.hostverify import HostBatchVerifier
    return HostBatchVerifier(scheme, pk)


def suppressed(scheme, pk):
    # tpu-vet: disable=verifier
    return BatchBeaconVerifier(scheme, pk)


# -- device enumeration (ISSUE 11): only crypto/device_pool.py may call
# jax.devices()/jax.local_devices() — everything below must be flagged

import jax
from jax import devices as jdevs


def direct_enumeration():
    return jax.devices()                            # VIOLATION


def local_enumeration():
    return jax.local_devices()                      # VIOLATION


def aliased_enumeration():
    return jdevs()                                  # VIOLATION: alias


def pool_route_is_fine():
    # the sanctioned path: NOT flagged
    from drand_tpu.crypto.device_pool import jax_devices
    return jax_devices()


def suppressed_enumeration():
    # tpu-vet: disable=verifier  (dryrun tooling probes the raw backend)
    return jax.devices()
