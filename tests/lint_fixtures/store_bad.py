"""Seeded store-contract violations (tests/test_vet.py fixture)."""

import sqlite3
import threading


class Store:
    """Stand-in for chain.store.Store (the checker matches the base name
    and its resolved import; fixtures stay import-free)."""

    DURABILITY = "volatile"


class NoDurabilityStore(Store):         # VIOLATION: missing DURABILITY
    def put(self, beacon):
        pass


class DeclaredStore(Store):             # fine
    DURABILITY = "crash-safe"


class UnlockedConnStore(Store):
    DURABILITY = "crash-safe"

    def __init__(self, path):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()

    def get(self, round_):
        row = self._conn.execute(       # VIOLATION: store-conn-unlocked
            "SELECT signature FROM beacons WHERE round = ?",
            (round_,)).fetchone()
        return row

    def last(self):
        with self._lock:
            return self._conn.execute(  # fine: lock held
                "SELECT round FROM beacons ORDER BY round DESC").fetchone()

    def put(self, beacon):              # VIOLATION: store-put-no-commit
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO beacons VALUES (?, ?)",
                (beacon.round, beacon.signature))

    def delete(self, round_):
        with self._lock:                # fine: mutates AND commits
            self._conn.execute(
                "DELETE FROM beacons WHERE round = ?", (round_,))
            self._conn.commit()


class ForeignConnCursor:
    """Cursor reaching into the store's connection without its lock."""

    def __init__(self, store):
        self._store = store

    def first(self):
        return self._store._conn.execute(   # VIOLATION: foreign conn, no lock
            "SELECT round FROM beacons ORDER BY round ASC").fetchone()

    def last(self):
        with self._store._lock:
            return self._store._conn.execute(   # fine: owner's lock held
                "SELECT round FROM beacons ORDER BY round DESC").fetchone()
