"""Occupancy campaign (ISSUE 10): depth-k dispatch pipelining, per-handle
lane-width/depth tuning (TUNING.json precedence), and the queue/device
latency split — all against stub backends so tier-1 compiles nothing.

CPU verdict-parity of the real crypto pipelines (depth-1 vs depth-k
streams, narrow vs wide pads, the fused recover) lives in
tests/test_batch.py / tests/test_partials.py — the conftest heavy
bucket — because those compile the pairing programs.
"""

import json
import os
import threading
import types

import numpy as np
import pytest

from drand_tpu.beacon.clock import FakeClock
from drand_tpu.crypto import tuning
from drand_tpu.crypto.verify_service import (DEFAULT_PAD, LANE_LIVE,
                                             VerifyService)

SCHEME = types.SimpleNamespace(id="stub-scheme")
PK = b"\x01" * 48


def stub_rule(round_, sig):
    return sig == b"sig-%d" % round_


def beacons(rng, bad=()):
    rounds = list(rng)
    sigs = [b"sig-%d" % r if r not in bad else b"forged" for r in rounds]
    return rounds, sigs, [None] * len(rounds)


class PipelinedStub:
    """pack/dispatch/resolve triple recorder (no jax)."""

    kind = "stub"
    pad_to = 0

    def __init__(self):
        self.calls = []
        self.stages = []

    def verify_batch(self, rounds, sigs, prev_sigs=None):
        self.calls.append(list(rounds))
        return np.array([stub_rule(r, s) for r, s in zip(rounds, sigs)],
                        dtype=bool)

    def pack_chunk(self, rounds, sigs, prev_sigs=None):
        self.stages.append(("pack", len(rounds)))
        return list(rounds), list(sigs)

    def dispatch_packed(self, packed):
        rounds, sigs = packed
        self.calls.append(list(rounds))
        self.stages.append(("dispatch", len(rounds)))
        return all(stub_rule(r, s) for r, s in zip(rounds, sigs))

    def resolve_packed(self, packed, verdict):
        rounds, sigs = packed
        self.stages.append(("resolve", len(rounds)))
        if verdict:
            return np.ones(len(rounds), dtype=bool)
        return np.array([stub_rule(r, s) for r, s in zip(rounds, sigs)],
                        dtype=bool)


def make_service(**kw):
    kw.setdefault("clock", FakeClock(1000.0))
    kw.setdefault("pad", 8)
    kw.setdefault("background_window", 0.0)
    return VerifyService(**kw)


# -- depth-k pipelined executor ----------------------------------------------


def test_depth_k_keeps_k_dispatches_in_flight():
    """With pipeline_depth=3, the executor enqueues up to 3 chunks ahead
    of the resolve point: the first resolve happens only after 4 chunks
    are dispatched (window full), not after 2 (the old double buffer)."""
    svc = make_service(pad=4, pipeline_depth=3)
    stub = PipelinedStub()
    h = svc.handle(SCHEME, PK, backend=stub)
    ok = h.verify_batch(*beacons(range(1, 21), bad={9}))   # 5 chunks of 4
    assert len(ok) == 20 and not ok[8] and ok.sum() == 19
    kinds = [k for k, _ in stub.stages if k != "pack"]
    assert kinds.index("resolve") == 4, kinds
    assert kinds.count("dispatch") == 5 and kinds.count("resolve") == 5
    st = svc.stats()
    assert st["inflight_depth_max"] == 4   # window + the advancing chunk
    svc.stop()


def test_depth_1_is_the_old_double_buffer():
    svc = make_service(pad=4, pipeline_depth=1)
    stub = PipelinedStub()
    h = svc.handle(SCHEME, PK, backend=stub)
    assert h.verify_batch(*beacons(range(1, 13))).all()    # 3 chunks
    kinds = [k for k, _ in stub.stages if k != "pack"]
    assert kinds == ["dispatch", "dispatch", "resolve", "dispatch",
                     "resolve", "resolve"]
    svc.stop()


def test_depth_parity_stub_verdicts_identical():
    """Same inputs through depth-1 and depth-4 services produce
    bit-identical verdicts (the coalescer/chunker is depth-agnostic)."""
    outs = {}
    for depth in (1, 4):
        svc = make_service(pad=4, pipeline_depth=depth)
        stub = PipelinedStub()
        h = svc.handle(SCHEME, PK, backend=stub)
        outs[depth] = h.verify_batch(*beacons(range(1, 31),
                                              bad={3, 17, 29}))
        svc.stop()
    assert (outs[1] == outs[4]).all()


def test_backend_footprint_cap_clamps_depth():
    """A backend exposing pipeline_depth() (BatchBeaconVerifier's
    VMEM-budget clamp) bounds the service's requested depth."""
    class Capped(PipelinedStub):
        asked = None

        def pipeline_depth(self, depth, pad):
            Capped.asked = (depth, pad)
            return 2

    svc = make_service(pad=4, pipeline_depth=64)
    h = svc.handle(SCHEME, PK, backend=Capped())
    assert h.verify_batch(*beacons(range(1, 25))).all()    # 6 chunks
    assert Capped.asked == (64, 4)
    kinds = [k for k, _ in h.backend.stages if k != "pack"]
    assert kinds.index("resolve") == 3     # window capped at 2, not 64
    svc.stop()


def test_verifier_pipeline_depth_math():
    """The real clamp: depth x per-chunk footprint <= the in-flight
    budget; no device work, just arithmetic on the constructed verifier."""
    from drand_tpu.crypto import batch
    from drand_tpu.crypto.schemes import scheme_from_name

    sch = scheme_from_name("bls-unchained-on-g1")
    _, pub = sch.keypair(seed=b"occupancy-depth")
    ver = batch.BatchBeaconVerifier(sch, sch.public_bytes(pub), pad_to=8192)
    assert ver.pipeline_depth(1, 8192) == 1
    cap = batch.max_pipeline_depth(8192, g2sig=False)
    assert ver.pipeline_depth(10 ** 6, 8192) == cap
    # G2 lanes are ~2x the bytes: same budget, smaller cap
    assert batch.max_pipeline_depth(8192, True) < cap
    assert batch.chunk_footprint_bytes(16384, False) \
        == 2 * batch.chunk_footprint_bytes(8192, False)


# -- watchdog: deadline on the oldest of a shared-device window ---------------


def test_watchdog_deadline_scales_with_inflight_window():
    svc = make_service(watchdog_floor=0.5, watchdog_factor=4.0)
    h = svc.handle(SCHEME, PK, backend=PipelinedStub(),
                   fallback=PipelinedStub())
    slot = svc._slots[h.key]
    slot.latencies.extend([0.1, 0.2, 1.0])
    assert svc._deadline_for(slot) == pytest.approx(4.0)
    # k dispatches share the device: the oldest ticket's budget covers
    # the window
    assert svc._deadline_for(slot, scale=3) == pytest.approx(12.0)
    # the cold-compile floor never scales
    slot.latencies.clear()
    assert svc._deadline_for(slot, scale=8) == 0.5
    svc.stop()


def test_watchdog_trips_only_the_oldest_ticket_per_slot():
    """Two tickets on one slot, both past deadline: only the OLDEST
    trips (younger work is judged once it becomes oldest — k in-flight
    dispatches are one shared-device window, not k independent hangs)."""
    from drand_tpu.crypto.verify_service import _Batch, _Ticket

    svc = make_service(watchdog_floor=5.0)
    h = svc.handle(SCHEME, PK, backend=PipelinedStub(),
                   fallback=PipelinedStub())
    slot = svc._slots[h.key]
    now = svc.clock.monotonic()
    old = _Ticket(slot, _Batch(LANE_LIVE), "chunk", now, now + 1.0)
    young = _Ticket(slot, _Batch(LANE_LIVE), "chunk", now + 0.5, now + 1.5)
    trips = []
    svc._trip = lambda t: trips.append(t)      # observe, don't failover
    with svc._cond:
        # start the watchdog (via the slot's group stream)
        svc._ensure_threads_locked(svc._stream_locked(slot.gid))
        svc._tickets[id(old)] = old
        svc._tickets[id(young)] = young
        svc._cond.notify_all()
    svc.clock.advance(2.0)                     # both past deadline
    deadline = threading.Event()
    for _ in range(100):
        if trips:
            break
        deadline.wait(0.05)
    assert [t is old for t in trips] == [True], trips
    assert not young.cancelled
    svc.stop()


# -- TUNING.json consultation (the autotune acceptance, no compiles) ---------


def _write_tuning(path, platform, kind, pad, depth):
    with open(path, "w") as f:
        json.dump({"version": 1, "entries":
                   {platform: {kind: {"pad": pad, "depth": depth}}}}, f)


def test_service_consults_tuning_file(tmp_path, monkeypatch):
    import jax
    tf = tmp_path / "TUNING.json"
    _write_tuning(tf, jax.default_backend(), "g1", 4, 3)
    monkeypatch.setenv("DRAND_TUNING_FILE", str(tf))
    monkeypatch.delenv("DRAND_VERIFY_PAD", raising=False)
    monkeypatch.delenv("DRAND_VERIFY_PIPELINE_DEPTH", raising=False)
    svc = make_service(pad=0)                  # AUTO: must consult
    stub = PipelinedStub()
    h = svc.handle(SCHEME, PK, backend=stub)
    assert h.verify_batch(*beacons(range(1, 11))).all()
    # the tuned pad drives the chunking: 10 rounds at pad 4 -> 4,4,2
    assert [len(c) for c in stub.calls] == [4, 4, 2]
    tun = next(iter(svc.stats()["tuning"].values()))
    assert (tun["pad"], tun["depth"]) == (4, 3)
    svc.stop()


def test_env_override_beats_tuning_file(tmp_path, monkeypatch):
    import jax
    tf = tmp_path / "TUNING.json"
    _write_tuning(tf, jax.default_backend(), "g1", 4, 3)
    monkeypatch.setenv("DRAND_TUNING_FILE", str(tf))
    monkeypatch.setenv("DRAND_VERIFY_PAD", "6")
    monkeypatch.setenv("DRAND_VERIFY_PIPELINE_DEPTH", "2")
    svc = make_service(pad=0)
    stub = PipelinedStub()
    h = svc.handle(SCHEME, PK, backend=stub)
    assert h.verify_batch(*beacons(range(1, 11))).all()
    assert [len(c) for c in stub.calls] == [6, 4]
    tun = next(iter(svc.stats()["tuning"].values()))
    assert (tun["pad"], tun["depth"]) == (6, 2)
    svc.stop()


def test_explicit_ctor_pad_pins_over_everything(tmp_path, monkeypatch):
    import jax
    tf = tmp_path / "TUNING.json"
    _write_tuning(tf, jax.default_backend(), "g1", 4, 3)
    monkeypatch.setenv("DRAND_TUNING_FILE", str(tf))
    monkeypatch.setenv("DRAND_VERIFY_PAD", "6")
    svc = make_service(pad=8, pipeline_depth=1)
    stub = PipelinedStub()
    h = svc.handle(SCHEME, PK, backend=stub)
    assert h.verify_batch(*beacons(range(1, 11))).all()
    assert [len(c) for c in stub.calls] == [8, 2]
    svc.stop()


def test_no_file_no_env_is_todays_default(monkeypatch):
    monkeypatch.delenv("DRAND_TUNING_FILE", raising=False)
    monkeypatch.delenv("DRAND_VERIFY_PAD", raising=False)
    monkeypatch.delenv("DRAND_VERIFY_PIPELINE_DEPTH", raising=False)
    monkeypatch.chdir("/tmp")                  # no cwd TUNING.json
    pad, depth, src = tuning.resolve("g2", "cpu")
    assert (pad, depth) == (DEFAULT_PAD, 1)
    assert src == "pad:default,depth:default"


def test_tuning_resolve_platform_scoped(tmp_path, monkeypatch):
    """A chip sweep's numbers never apply to another platform."""
    tf = tmp_path / "TUNING.json"
    _write_tuning(tf, "tpu", "g2", 32768, 4)
    monkeypatch.setenv("DRAND_TUNING_FILE", str(tf))
    monkeypatch.delenv("DRAND_VERIFY_PAD", raising=False)
    monkeypatch.delenv("DRAND_VERIFY_PIPELINE_DEPTH", raising=False)
    assert tuning.resolve("g2", "tpu")[:2] == (32768, 4)
    assert tuning.resolve("g2", "cpu")[:2] == (DEFAULT_PAD, 1)
    assert tuning.resolve("g1", "tpu")[:2] == (DEFAULT_PAD, 1)


def test_tuning_malformed_file_is_ignored(tmp_path, monkeypatch):
    tf = tmp_path / "TUNING.json"
    tf.write_text("{not json")
    monkeypatch.setenv("DRAND_TUNING_FILE", str(tf))
    monkeypatch.delenv("DRAND_VERIFY_PAD", raising=False)
    monkeypatch.delenv("DRAND_VERIFY_PIPELINE_DEPTH", raising=False)
    assert tuning.resolve("g1", "cpu")[:2] == (DEFAULT_PAD, 1)


def test_tuning_resolve_keyed_by_group_size(tmp_path, monkeypatch):
    """ISSUE 11: a `<kind>@<n>` entry is the n-device-group winner and
    beats the bare kind; sizes with no sweep fall back to the bare kind
    (the legacy 1-device spelling)."""
    tf = tmp_path / "TUNING.json"
    with open(tf, "w") as f:
        json.dump({"version": 1, "entries": {"cpu": {
            "g1": {"pad": 64, "depth": 1},
            "g1@4": {"pad": 256, "depth": 2}}}}, f)
    monkeypatch.setenv("DRAND_TUNING_FILE", str(tf))
    monkeypatch.delenv("DRAND_VERIFY_PAD", raising=False)
    monkeypatch.delenv("DRAND_VERIFY_PIPELINE_DEPTH", raising=False)
    assert tuning.resolve("g1", "cpu", group_size=1)[:2] == (64, 1)
    assert tuning.resolve("g1", "cpu", group_size=4)[:2] == (256, 2)
    # no @2 sweep: the bare-kind fallback serves
    assert tuning.resolve("g1", "cpu", group_size=2)[:2] == (64, 1)
    # a different-platform @4 entry never leaks
    assert tuning.resolve("g1", "tpu", group_size=4)[:2] \
        == (DEFAULT_PAD, 1)


def test_service_resolves_tuning_for_its_group_size(tmp_path, monkeypatch):
    """A handle whose device group owns 2 devices resolves the g1@2
    winner, not the 1-device entry."""
    import jax
    tf = tmp_path / "TUNING.json"
    with open(tf, "w") as f:
        json.dump({"version": 1, "entries": {jax.default_backend(): {
            "g1": {"pad": 4, "depth": 1},
            "g1@2": {"pad": 6, "depth": 2}}}}, f)
    monkeypatch.setenv("DRAND_TUNING_FILE", str(tf))
    monkeypatch.delenv("DRAND_VERIFY_PAD", raising=False)
    monkeypatch.delenv("DRAND_VERIFY_PIPELINE_DEPTH", raising=False)
    svc = make_service(pad=0, device_groups=4)     # 8 devices -> 2 each
    stub = PipelinedStub()
    h = svc.handle(SCHEME, PK, backend=stub)
    assert h.verify_batch(*beacons(range(1, 11))).all()
    assert [len(c) for c in stub.calls] == [6, 4]  # the @2 pad drives
    tun = next(iter(svc.stats()["tuning"].values()))
    assert (tun["pad"], tun["depth"]) == (6, 2)
    svc.stop()


def test_write_tuning_merges_platforms(tmp_path):
    tf = str(tmp_path / "TUNING.json")
    tuning.write_tuning(tf, "cpu", {"g1": {"pad": 64, "depth": 1}})
    tuning.write_tuning(tf, "tpu", {"g2": {"pad": 32768, "depth": 4}})
    ent = tuning.load_entries(tf)
    assert ent["cpu"]["g1"]["pad"] == 64
    assert ent["tpu"]["g2"]["depth"] == 4


# -- the dispatch-latency split ----------------------------------------------


def test_stats_carry_queue_device_split_and_summary():
    svc = make_service(pad=4, background_window=100.0)
    stub = PipelinedStub()
    h = svc.handle(SCHEME, PK, backend=stub)
    f = h.submit(*beacons([1, 2]))
    svc.clock.advance(101.0)                   # window expiry = queue time
    assert f.result(10).all()
    st = svc.stats()
    assert st["queue_time_s"] >= 100.0         # the fake-clock window wait
    assert st["device_time_s"] >= 0.0
    assert st["pack_time_s"] >= 0.0            # the ISSUE 14 pack term
    assert "inflight_depth_max" in st
    s = svc.summary()
    assert "inflight<=" in s and "pt/qt/dt=" in s
    svc.stop()


def test_health_payload_carries_occupancy_fields():
    """/health surfaces the inflight gauge + latency split (the fields,
    not a daemon e2e — that path is covered by test_daemon_e2e)."""
    svc = make_service(pad=4)
    h = svc.handle(SCHEME, PK, backend=PipelinedStub())
    assert h.verify_batch(*beacons([1])).all()
    st = svc.stats()
    payload = {"verify_inflight_depth": st["inflight_depth_max"],
               "verify_latency_split": {"pack_s": st["pack_time_s"],
                                        "queue_s": st["queue_time_s"],
                                        "device_s": st["device_time_s"]}}
    assert set(payload["verify_latency_split"]) == \
        {"pack_s", "queue_s", "device_s"}
    svc.stop()


def test_metrics_series_exist():
    from drand_tpu import metrics
    metrics.verify_inflight.set(3)
    metrics.verify_dispatch_latency.labels("live", "queue").observe(0.1)
    metrics.verify_dispatch_latency.labels("live", "device").observe(0.2)
    metrics.verify_dispatch_latency.labels("live", "pack").observe(0.05)
    blob = metrics.scrape("private").decode()
    assert "verify_service_inflight_depth 3.0" in blob
    assert 'verify_service_dispatch_latency_seconds_count{lane="live",phase="queue"}' in blob
    assert 'verify_service_dispatch_latency_seconds_count{lane="live",phase="pack"}' in blob
