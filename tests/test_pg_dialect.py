"""Postgres dialect transcript golden (VERDICT r4 #6).

No postgres server can exist in this environment (zero egress, no
daemon), so the closest honest equivalent of the reference's live-server
matrix run (test/dbtest.go:119, test/docker.go:97) is a TRANSCRIPT test:
record the exact SQL + parameter stream `PostgresStore` emits through
the driver boundary, and assert every statement against the psycopg2
dialect rules a live server would enforce:

  * placeholders are `%s` only (psycopg2 interpolates with Python
    %-formatting — `?` reaches the server as a syntax error, and a bare
    `%` not part of `%s` crashes the client before the server sees it);
  * parameter count matches placeholder count per statement;
  * bytea parameters are `bytes` (psycopg2 adapts bytes; str would be
    sent as text and fail the column type);
  * `ON CONFLICT ... DO UPDATE` requires a conflict target;
  * the statement stream for the canonical CRUD sequence is pinned, so
    a store edit that changes what is sent to the server fails HERE
    with a readable diff, not on a hypothetical deployment.
"""

import re

from drand_tpu.chain import _pgcompat
from drand_tpu.chain.beacon import Beacon
from drand_tpu.chain.postgresdb import PostgresStore


class _RecordingDriver:
    """psycopg2-shaped driver that records (sql, params) at the store
    boundary, then delegates to the sqlite-backed shim."""

    def __init__(self):
        self.transcript = []

    def connect(self, dsn):
        drv = self
        inner = _pgcompat.connect(dsn)

        class Conn:
            autocommit = False

            def cursor(self):
                icur = inner.cursor()

                class Cur:
                    def execute(self, sql, args=()):
                        drv.transcript.append((sql, tuple(args)))
                        return icur.execute(sql, args)

                    def fetchone(self):
                        return icur.fetchone()

                    def fetchall(self):
                        return icur.fetchall()

                    def close(self):
                        icur.close()

                    def __enter__(self):
                        return self

                    def __exit__(self, *exc):
                        self.close()
                        return False

                return Cur()

            def commit(self):
                inner.commit()

            def rollback(self):
                inner.rollback()

            def close(self):
                inner.close()

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return inner.__exit__(*exc)

        return Conn()


def _norm(sql):
    return re.sub(r"\s+", " ", sql).strip()


_STRIP_LIT = _pgcompat.LITERAL_RE


def _assert_psycopg2_clean(sql, args):
    bare = re.sub(_STRIP_LIT, "", sql)
    assert "?" not in bare, f"sqlite placeholder in: {sql}"
    # psycopg2 interpolates with %-formatting: every % must be part of %s
    assert re.fullmatch(r"[^%]*(?:%s[^%]*)*", bare), \
        f"stray % (psycopg2 would crash formatting): {sql}"
    assert bare.count("%s") == len(args), \
        f"placeholder/param mismatch: {sql} <- {args!r}"
    m = re.search(r"ON CONFLICT\s*(\(.*?\))?\s*DO UPDATE", bare, re.I)
    if m:
        assert m.group(1), f"DO UPDATE without conflict target: {sql}"
    for a in args:
        assert isinstance(a, (int, str, bytes)), \
            f"psycopg2 cannot adapt {type(a).__name__} in {sql}"


# The pinned statement stream for the canonical CRUD sequence below.
# Parameters are pinned by TYPE (psycopg2 adaptation class), not value.
_GOLDEN = [
    # constructor: schema + beacon-id registration
    ("CREATE TABLE IF NOT EXISTS beacons ( beacon_id INT NOT NULL, round "
     "BIGINT NOT NULL, signature BYTEA NOT NULL, PRIMARY KEY (beacon_id, "
     "round) ); CREATE TABLE IF NOT EXISTS beacon_ids ( id SERIAL PRIMARY "
     "KEY, name TEXT UNIQUE NOT NULL ); CREATE TABLE IF NOT EXISTS "
     "beacons_quarantine ( beacon_id INT NOT NULL, round BIGINT NOT NULL, "
     "signature BYTEA NOT NULL, PRIMARY KEY (beacon_id, round) );", ()),
    ("INSERT INTO beacon_ids (name) VALUES (%s) ON CONFLICT (name) "
     "DO NOTHING", (str,)),
    ("SELECT id FROM beacon_ids WHERE name = %s", (str,)),
    # put x2
    ("INSERT INTO beacons (beacon_id, round, signature) VALUES (%s, %s, %s) "
     "ON CONFLICT DO NOTHING", (int, int, bytes)),
    ("INSERT INTO beacons (beacon_id, round, signature) VALUES (%s, %s, %s) "
     "ON CONFLICT DO NOTHING", (int, int, bytes)),
    # get(2) + chained previous reconstruction (trimmed format)
    ("SELECT signature FROM beacons WHERE beacon_id=%s AND round=%s",
     (int, int)),
    ("SELECT signature FROM beacons WHERE beacon_id=%s AND round=%s",
     (int, int)),
    # last()
    ("SELECT round, signature FROM beacons WHERE beacon_id=%s ORDER BY "
     "round DESC LIMIT 1", (int,)),
    ("SELECT signature FROM beacons WHERE beacon_id=%s AND round=%s",
     (int, int)),
    # len()
    ("SELECT count(*) FROM beacons WHERE beacon_id=%s", (int,)),
    # cursor: first, next, seek
    ("SELECT round, signature FROM beacons WHERE beacon_id=%s ORDER BY "
     "round ASC LIMIT 1", (int,)),
    ("SELECT signature FROM beacons WHERE beacon_id=%s AND round=%s",
     (int, int)),
    ("SELECT round, signature FROM beacons WHERE beacon_id=%s AND round > "
     "%s ORDER BY round ASC LIMIT 1", (int, int)),
    ("SELECT signature FROM beacons WHERE beacon_id=%s AND round=%s",
     (int, int)),
    ("SELECT round, signature FROM beacons WHERE beacon_id=%s AND round >= "
     "%s ORDER BY round ASC LIMIT 1", (int, int)),
    ("SELECT signature FROM beacons WHERE beacon_id=%s AND round=%s",
     (int, int)),
    # delete
    ("DELETE FROM beacons WHERE beacon_id=%s AND round=%s", (int, int)),
    # tombstone (two-phase quarantine): probe, replace-move, delete
    ("SELECT 1 FROM beacons WHERE beacon_id=%s AND round=%s", (int, int)),
    ("DELETE FROM beacons_quarantine WHERE beacon_id=%s AND round=%s",
     (int, int)),
    ("INSERT INTO beacons_quarantine (beacon_id, round, signature) SELECT "
     "beacon_id, round, signature FROM beacons WHERE beacon_id=%s AND "
     "round=%s", (int, int)),
    ("DELETE FROM beacons WHERE beacon_id=%s AND round=%s", (int, int)),
    # tombstoned + drop_tombstone
    ("SELECT signature FROM beacons_quarantine WHERE beacon_id=%s AND "
     "round=%s", (int, int)),
    ("DELETE FROM beacons_quarantine WHERE beacon_id=%s AND round=%s",
     (int, int)),
]


def test_pg_transcript_golden(tmp_path):
    drv = _RecordingDriver()
    s = PostgresStore(str(tmp_path / "pg.db"), driver=drv,
                      require_previous=True)
    s.put(Beacon(round=1, signature=b"\x01" * 96))
    s.put(Beacon(round=2, signature=b"\x02" * 96, previous_sig=b"\x01" * 96))
    got = s.get(2)
    assert got.previous_sig == b"\x01" * 96
    assert s.last().round == 2
    assert len(s) == 2
    cur = s.cursor()
    assert cur.first().round == 1
    assert cur.next().round == 2
    assert cur.seek(2).round == 2
    s.delete(1)
    assert s.tombstone(2) is True
    assert s.tombstoned(2).signature == b"\x02" * 96
    s.drop_tombstone(2)
    s.close()

    for sql, args in drv.transcript:
        _assert_psycopg2_clean(sql, args)

    got_stream = [(_norm(sql), tuple(type(a) for a in args))
                  for sql, args in drv.transcript]
    assert got_stream == _GOLDEN


def test_pgcompat_literal_escape():
    """The shim's placeholder guard must parse doubled-quote escapes: a
    '?' inside a postgres string literal (even one containing an escaped
    quote) is data, not a placeholder."""
    assert "?" not in re.sub(_STRIP_LIT, "", "SELECT 'it''s ok?'")
    _pgcompat._translate("SELECT 'it''s ok?'")  # must not raise
