"""Aux subsystems: metrics server, threshold monitor, structured logging,
entropy (SURVEY.md §5.1/§5.3/§5.5)."""

import io
import json
import time
import urllib.request

from drand_tpu import log as dlog
from drand_tpu.entropy import ScriptReader, get_random
from drand_tpu.metrics import (MetricsServer, ThresholdMonitor,
                               beacon_discrepancy_latency, last_beacon_round,
                               scrape, scrape_all)


def test_metrics_registries_and_series():
    last_beacon_round.labels("auxtest").set(42)
    beacon_discrepancy_latency.labels("auxtest").set(12.5)
    text = scrape("group").decode()
    assert 'last_beacon_round{beacon_id="auxtest"} 42.0' in text
    assert "beacon_discrepancy_latency" in text
    assert scrape_all()          # all four registries concatenate


def test_metrics_server_routes():
    srv = MetricsServer(0)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "last_beacon_round" in body
        body = urllib.request.urlopen(f"{base}/metrics/group").read().decode()
        assert "group_size" in body
        assert b"GC run" in urllib.request.urlopen(f"{base}/debug/gc").read()
        # pprof-equivalent stack dump names this very thread
        dump = urllib.request.urlopen(f"{base}/debug/pprof").read().decode()
        assert "Thread" in dump
    finally:
        srv.stop()


def test_threshold_monitor_escalation():
    stream = io.StringIO()
    dlog.configure(level="debug", json_output=True, stream=stream)
    try:
        log = dlog.Logger("thr-test")
        mon = ThresholdMonitor("auxtest", log, threshold=2, period=0.1)
        mon.start()
        mon.report_failure("10.0.0.1:1")
        mon.report_failure("10.0.0.2:1")
        time.sleep(0.4)
        mon.stop()
        events = [json.loads(line) for line in
                  stream.getvalue().splitlines() if line.strip()]
        errors = [e for e in events if e["level"] == "ERROR"]
        assert errors and errors[0]["failures"] == 2
    finally:
        dlog.configure()


def test_structured_logger_named_fields():
    stream = io.StringIO()
    dlog.configure(level="info", json_output=True, stream=stream)
    try:
        log = dlog.Logger("daemon").named("default").with_fields(index=3)
        log.info("beacon stored", round=7)
        rec = json.loads(stream.getvalue())
        assert rec["logger"] == "daemon.default"
        assert rec["index"] == 3 and rec["round"] == 7
        assert rec["msg"] == "beacon stored"
    finally:
        dlog.configure()


def test_rate_limited_info():
    stream = io.StringIO()
    dlog.configure(level="info", json_output=True, stream=stream)
    try:
        log = dlog.Logger("bulk")
        for _ in range(dlog.LOGS_TO_SKIP * 2):
            log.rate_limited_info("syncing")
        lines = [l for l in stream.getvalue().splitlines() if l.strip()]
        assert len(lines) == 2       # one per LOGS_TO_SKIP window
    finally:
        dlog.configure()


def test_entropy_sources(tmp_path):
    assert len(get_random(None, 32)) == 32
    script = tmp_path / "entropy.sh"
    script.write_text("#!/bin/sh\nprintf 'abcdefgh'\n")
    script.chmod(0o755)
    reader = ScriptReader(str(script))
    out = reader.read(20)
    assert out == (b"abcdefgh" * 3)[:20]
    # failing script falls back to the CSPRNG without raising
    bad = tmp_path / "bad.sh"
    bad.write_text("#!/bin/sh\nexit 1\n")
    bad.chmod(0o755)
    assert len(get_random(ScriptReader(str(bad)), 16)) == 16


def test_accel_probe_backend_cpu():
    """probe_backend must pin the platform at config level inside the
    probe interpreter (env vars are overridden by the axon sitecustomize)
    and report backend + device count without touching this process's
    backend state."""
    from drand_tpu.accel import probe_backend

    info, detail = probe_backend(timeout=120, platform="cpu")
    assert info is not None, detail
    assert info["backend"] == "cpu"
    assert info["devices"] >= 1
    assert "cpu" in detail


def test_accel_probe_backend_failure_modes():
    from drand_tpu.accel import probe_backend

    # a probe whose backend init fails must report the stderr tail, not
    # hang or raise into the caller
    info, detail = probe_backend(timeout=120, platform="no_such_platform")
    assert info is None
    assert detail
