"""Multi-tenant serving (core/tenancy.py, ISSUE 15): the tenant
registry, the admission sub-budgets, tenant-aware device placement, the
REST/Control surfaces, and the quota edge cases the issue names (paused
tenant, mid-flight removal, torn registry file)."""

import json
import os
import threading
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from drand_tpu.beacon.clock import FakeClock
from drand_tpu.core.tenancy import (DEFAULT_TENANT, TenantConfig,
                                    TenantRegistry, registry_path)
from drand_tpu.net.admission import (AdmissionController, Shed,
                                     CLASS_CRITICAL, CLASS_NORMAL,
                                     CLASS_SHEDDABLE, LEVEL_SHED_PUBLIC,
                                     REASON_LEVEL, REASON_TENANT_LEVEL,
                                     REASON_TENANT_PAUSED,
                                     REASON_TENANT_RATE,
                                     REASON_TENANT_SHARE)

SCHEME = types.SimpleNamespace(id="stub-scheme")


def pk(i: int) -> bytes:
    return bytes([i]) * 48


def mk_registry(tmp_path, clock=None, window=30.0):
    return TenantRegistry(path=str(tmp_path / "tenants.json"),
                          clock=clock or FakeClock(1000.0),
                          device_window=window)


def mk_ctrl(reg, clock, **kw):
    kw.setdefault("capacity", 8)
    kw.setdefault("critical_reserve", 2)
    return AdmissionController(clock=clock, tenancy=reg, **kw)


# ---------------------------------------------------------------------------
# registry: CRUD, resolution, persistence, torn-write recovery
# ---------------------------------------------------------------------------


def test_registry_crud_and_resolution(tmp_path):
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    reg.set_tenant(TenantConfig(name="acme", weight=2.0,
                                chains=("default", "c2")))
    reg.register_chain("default", pk=pk(1), chain_hash="ab" * 32)
    assert reg.tenant_for_chain("default") == "acme"
    assert reg.tenant_for_chain("c2") == "acme"
    assert reg.tenant_for_hash("ab" * 32) == "acme"
    assert reg.tenant_for_pk(pk(1)) == "acme"
    # unknown chains belong to the implicit default tenant
    assert reg.tenant_for_chain("other") == DEFAULT_TENANT
    assert reg.tenant_for_pk(pk(9)) == DEFAULT_TENANT
    # update (upsert) replaces; remove falls back to default
    reg.set_tenant(TenantConfig(name="acme", weight=5.0, chains=("c2",)))
    assert reg.get("acme").weight == 5.0
    assert reg.tenant_for_chain("default") == DEFAULT_TENANT
    assert reg.remove_tenant("acme") and not reg.remove_tenant("acme")
    assert reg.tenant_for_chain("c2") == DEFAULT_TENANT


def test_registry_resolve_grpc_metadata(tmp_path):
    reg = mk_registry(tmp_path)
    reg.set_tenant(TenantConfig(name="t", chains=("beta",)))
    reg.register_chain("beta", chain_hash="cd" * 32)
    meta = types.SimpleNamespace(beaconID="beta", chain_hash=b"")
    assert reg.resolve_metadata(meta) == "t"
    meta = types.SimpleNamespace(beaconID="", chain_hash=bytes.fromhex(
        "cd" * 32))
    assert reg.resolve_metadata(meta) == "t"
    assert reg.resolve_metadata(None) == DEFAULT_TENANT


def test_registry_persists_atomically_and_reloads(tmp_path):
    reg = mk_registry(tmp_path)
    reg.set_tenant(TenantConfig(name="acme", weight=2.0, rate=10.0,
                                burst=5, device_budget=0.25,
                                chains=("default",), pin_group=3,
                                anti_affinity=True))
    path = str(tmp_path / "tenants.json")
    assert os.path.exists(path)
    # no stray temp files: fs.write_atomic cleans up after itself
    leftovers = [f for f in os.listdir(tmp_path) if f != "tenants.json"]
    assert leftovers == []
    fresh = mk_registry(tmp_path)
    cfg = fresh.get("acme")
    assert cfg is not None and cfg.weight == 2.0 and cfg.rate == 10.0
    assert cfg.burst == 5 and cfg.device_budget == 0.25
    assert cfg.chains == ("default",) and cfg.pin_group == 3
    assert cfg.anti_affinity and not cfg.paused
    assert fresh.tenant_for_chain("default") == "acme"


def test_registry_torn_write_recovery(tmp_path):
    """A torn/corrupt tenants.json (out-of-band writer, disk fault —
    our own writes ride fs.write_atomic) must not brick the daemon: the
    bytes are parked at .corrupt, the registry starts empty (unmetered),
    the load error is visible in the snapshot, and the next save writes
    a clean file."""
    path = str(tmp_path / "tenants.json")
    with open(path, "w") as f:
        f.write('{"version": 1, "tenants": [{"name": "ac')   # torn
    reg = mk_registry(tmp_path)
    assert reg.names() == []
    assert os.path.exists(path + ".corrupt")
    assert not os.path.exists(path)
    assert "load_error" in reg.snapshot()
    reg.set_tenant(TenantConfig(name="fresh"))
    assert mk_registry(tmp_path).names() == ["fresh"]


def test_registry_change_listeners_fire_outside_lock(tmp_path):
    reg = mk_registry(tmp_path)
    seen = []
    reg.on_change(lambda: seen.append(reg.names()))   # re-enters registry
    reg.set_tenant(TenantConfig(name="a"))
    reg.remove_tenant("a")
    assert seen == [["a"], []]


# ---------------------------------------------------------------------------
# admission sub-budgets
# ---------------------------------------------------------------------------


def test_paused_tenant_sheds_well_formed_without_device_time(tmp_path):
    """The zero-quota (admin-paused) edge case: everything non-critical
    sheds with a well-formed, tenant-labelled rejection; critical is
    exempt; and nothing of the tenant's touches device time."""
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    reg.set_tenant(TenantConfig(name="z", weight=0.0, chains=("zc",)))
    ctrl = mk_ctrl(reg, clock)
    for cls in (CLASS_SHEDDABLE, CLASS_NORMAL):
        with pytest.raises(Shed) as ei:
            ctrl.admit(cls, tenant="z")
        s = ei.value
        assert s.reason == REASON_TENANT_PAUSED
        assert s.tenant == "z" and s.retry_after > 0
        assert "z" in str(s) and s.cls == cls
    # critical (the chain's own partials) is never shed on quota grounds
    ctrl.admit(CLASS_CRITICAL, tenant="z").release()
    # paused tenant accumulated zero device seconds: placement weighs it
    # at 0 and its reads never reached a verify handle
    assert reg.device_seconds("z") == 0.0
    snap = reg.snapshot()["tenants"]["z"]
    assert snap["paused"] and snap["shed"] == 2 and snap["admitted"] == 1
    assert snap["device_seconds_total"] == 0.0


def test_tenant_rate_bucket_refills_on_injected_clock(tmp_path):
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    reg.set_tenant(TenantConfig(name="r", rate=2.0, burst=2))
    ctrl = mk_ctrl(reg, clock)
    ctrl.admit(CLASS_SHEDDABLE, tenant="r").release()
    ctrl.admit(CLASS_SHEDDABLE, tenant="r").release()
    with pytest.raises(Shed) as ei:
        ctrl.admit(CLASS_SHEDDABLE, tenant="r")
    assert ei.value.reason == REASON_TENANT_RATE
    assert ei.value.tenant == "r"
    clock.advance(0.5)          # 2/s x 0.5 s -> one token back
    ctrl.admit(CLASS_SHEDDABLE, tenant="r").release()
    with pytest.raises(Shed):
        ctrl.admit(CLASS_SHEDDABLE, tenant="r")
    # the bucket is per tenant: another tenant is untouched
    ctrl.admit(CLASS_SHEDDABLE, tenant="other").release()


def test_over_quota_tenant_sheds_one_rung_early(tmp_path):
    """Device budget spent -> the tenant is judged one ladder rung higher
    than the actual level: its sheddable reads shed at nominal while a
    compliant tenant's are served, and the reason distinguishes the
    tenant bump (tenant-level) from real ladder pressure (level)."""
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock, window=10.0)
    reg.set_tenant(TenantConfig(name="pig", device_budget=0.1))   # 1 s/10 s
    ctrl = mk_ctrl(reg, clock)
    ctrl.admit(CLASS_SHEDDABLE, tenant="pig").release()   # under quota: ok
    reg.account_device_time("pig", 5.0)                   # 5x the budget
    assert reg.quota_level("pig") >= 1.0
    with pytest.raises(Shed) as ei:
        ctrl.admit(CLASS_SHEDDABLE, tenant="pig")
    assert ei.value.reason == REASON_TENANT_LEVEL
    assert ei.value.tenant == "pig"
    # compliant tenants still flow at nominal
    ctrl.admit(CLASS_SHEDDABLE, tenant="nice").release()
    # at a real ladder level the reason is the plain ladder one
    with ctrl._cond:
        ctrl._level = LEVEL_SHED_PUBLIC
    with pytest.raises(Shed) as ei:
        ctrl.admit(CLASS_SHEDDABLE, tenant="pig")
    assert ei.value.reason == REASON_LEVEL
    # the quota window rolls: the spend ages out and the tenant recovers
    with ctrl._cond:
        ctrl._level = 0
    clock.advance(11.0)
    assert reg.quota_level("pig") == 0.0
    ctrl.admit(CLASS_SHEDDABLE, tenant="pig").release()


def test_weighted_fair_share_under_contention(tmp_path):
    """WFQ inside the class: with the noncritical pool full, a tenant
    already holding its weight-proportional share is shed immediately
    (tenant-share) instead of camping on the wait, and the token a
    compliant tenant was waiting for reaches it."""
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    reg.set_tenant(TenantConfig(name="hog", weight=1.0))
    reg.set_tenant(TenantConfig(name="fair", weight=1.0))
    ctrl = mk_ctrl(reg, clock, capacity=6, critical_reserve=2,
                   normal_wait=30.0)
    limit = ctrl.capacity - ctrl.critical_reserve       # 4 tokens
    held = [ctrl.admit(CLASS_NORMAL, tenant="hog") for _ in range(limit)]
    # the hog's next request finds the pool full AND itself over-share:
    # immediate tenant-share shed, no normal_wait camp
    t0 = clock.monotonic()
    with pytest.raises(Shed) as ei:
        ctrl.admit(CLASS_NORMAL, tenant="hog")
    assert ei.value.reason == REASON_TENANT_SHARE
    assert ei.value.tenant == "hog"
    assert clock.monotonic() == t0          # no fake-time wait burned
    # a compliant tenant (zero holdings) waits and wins the next release
    got = []

    def fair():
        got.append(ctrl.admit(CLASS_NORMAL, tenant="fair"))

    th = threading.Thread(target=fair, daemon=True)
    th.start()
    threading.Event().wait(0.1)
    assert not got                          # pool genuinely full
    held.pop().release()
    th.join(timeout=5)
    assert got, "released token must reach the under-share tenant"
    got[0].release()
    for t in held:
        t.release()
    assert ctrl.snapshot()["tenant_inflight"] == {}


def test_tenant_removal_mid_flight_requeues_nothing(tmp_path):
    """Quota edge case: a tenant removed while its requests are in
    flight — the held ticket releases cleanly, later accounting for the
    dead name lands on the implicit default view (never a KeyError, no
    resurrection of the dead entry), and new requests resolve against
    default."""
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    reg.set_tenant(TenantConfig(name="gone", rate=100.0, chains=("gc",)))
    ctrl = mk_ctrl(reg, clock)
    held = ctrl.admit(CLASS_SHEDDABLE, tenant="gone")
    assert ctrl.snapshot()["tenant_inflight"] == {"gone": 1}
    assert reg.remove_tenant("gone")
    held.release()          # in-flight ticket of a dead entry: clean
    assert ctrl.snapshot()["tenant_inflight"] == {}
    # device time attributed to the dead name is absorbed, not requeued
    # into a registry entry (and never raises)
    reg.account_device_time("gone", 1.0)
    assert reg.quota_level("gone") == 0.0
    assert "gone" not in reg.snapshot()["tenants"]
    # the chain now resolves to the implicit default tenant
    assert reg.tenant_for_chain("gc") == DEFAULT_TENANT
    ctrl.admit(CLASS_SHEDDABLE, tenant=reg.tenant_for_chain("gc")).release()


def test_untenanted_call_sites_unchanged(tmp_path):
    """tenant=None (every pre-tenancy call site) never consults the
    registry — behavior stays byte-identical."""
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    reg.set_tenant(TenantConfig(name="z", weight=0.0))
    ctrl = mk_ctrl(reg, clock)
    ctrl.admit(CLASS_SHEDDABLE).release()
    ctrl.admit(CLASS_NORMAL).release()
    assert ctrl.snapshot()["tenant_inflight"] == {}


# ---------------------------------------------------------------------------
# placement: weight-proportional groups, pinning, anti-affinity, rebalance
# ---------------------------------------------------------------------------


class _Dev:
    pass


@pytest.fixture
def fake_pool():
    from drand_tpu.crypto.device_pool import (DevicePool,
                                              _reset_inventory_for_tests)
    _reset_inventory_for_tests([_Dev() for _ in range(4)])
    yield DevicePool()          # 4 groups of 1
    _reset_inventory_for_tests(None)


def test_pool_weight_proportional_assignment(fake_pool):
    pool = fake_pool
    g_heavy = pool.assign("heavy", tenant="big", weight=3.0)
    # the weight-3 chain loads its group 3x: the next three weight-1
    # chains all land elsewhere before anyone shares with it
    light = [pool.assign(f"l{i}", tenant="small", weight=1.0)
             for i in range(3)]
    assert all(g.gid != g_heavy.gid for g in light)
    loads = pool.loads()
    assert loads[g_heavy.gid] == 3.0


def test_pool_pin_and_anti_affinity(fake_pool):
    pool = fake_pool
    pool.assign("a", tenant="ta", weight=1.0)
    pool.assign("b", tenant="tb", weight=1.0)
    pinned = pool.assign("p", tenant="prem", weight=1.0, pin=3)
    assert pinned.gid == 3
    # anti-affinity prefers a group no OTHER tenant occupies
    iso = pool.assign("i", tenant="iso", weight=1.0, anti_affinity=True)
    assert iso.gid not in {pool.gid_of("a"), pool.gid_of("b"),
                           pool.gid_of("p")}
    # out-of-range pin is ignored, not an error
    ok = pool.assign("q", tenant="prem", weight=1.0, pin=99)
    assert 0 <= ok.gid < 4
    snap = pool.snapshot()
    assert snap[3]["tenants"] == ["prem"]


def test_service_places_and_accounts_by_tenant(tmp_path, fake_pool):
    """End to end through the verify service: the handle lands on the
    tenant's pinned group, and a dispatch's measured device time is
    attributed to the tenant off the pack|queue|device split."""
    from drand_tpu.crypto.verify_service import (LANE_LIVE, VerifyService)
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock, window=100.0)
    reg.set_tenant(TenantConfig(name="prem", device_budget=1.0,
                                chains=("premchain",), pin_group=2))
    reg.register_chain("premchain", pk=pk(7))
    svc = VerifyService(clock=clock, pad=8, background_window=0.0,
                        pool=fake_pool)
    svc.set_tenancy(reg)

    class CostedBackend:
        kind = "stub"

        def verify_batch(self, rounds, sigs, prev_sigs=None):
            clock.advance(0.25)         # the measured "device" interval
            return np.ones(len(rounds), dtype=bool)

    try:
        h = svc.handle(SCHEME, pk(7), backend=CostedBackend())
        assert h.gid == 2, "tenant pin must drive handle placement"
        out = h.verify_batch([1, 2, 3], [b"s"] * 3, lane=LANE_LIVE)
        assert out.all()
        assert reg.device_seconds("prem") == pytest.approx(0.25)
        assert svc.stats()["tenant_map"] == {
            f"stub-scheme:{pk(7)[:4].hex()}": "prem"}
        assert svc.stats()["group_map"][
            f"stub-scheme:{pk(7)[:4].hex()}"] == 2
    finally:
        svc.stop()


def test_service_rebalances_on_pin_change(tmp_path, fake_pool):
    """Tenant update moves a pinned chain: rebalance_tenants rebuilds the
    backend on the target group (the _migrate discipline) and the pool
    affinity follows."""
    from drand_tpu.crypto.verify_service import VerifyService
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    reg.set_tenant(TenantConfig(name="mv", chains=("mvchain",),
                                pin_group=0))
    reg.register_chain("mvchain", pk=pk(5))
    svc = VerifyService(clock=clock, pad=8, background_window=0.0,
                        pool=fake_pool)
    svc.set_tenancy(reg)
    built = []

    def factory(group):
        built.append(group.gid)

        class B:
            kind = "stub"

            def verify_batch(self, rounds, sigs, prev_sigs=None):
                return np.ones(len(rounds), dtype=bool)
        return B()

    try:
        h = svc.handle(SCHEME, pk(5), backend_factory=factory)
        assert h.gid == 0 and built == [0]
        reg.set_tenant(TenantConfig(name="mv", chains=("mvchain",),
                                    pin_group=3))
        moved = svc.rebalance_tenants()
        assert moved == 1 and built == [0, 3]
        assert svc._slots[h.key].gid == 3
        assert fake_pool.gid_of(h.key) == 3
        assert svc.stats()["tenant_rebalances"] == 1
        # verdicts still flow on the rebuilt backend
        assert h.verify_batch([1], [b"x"]).all()
        # removing the tenant un-labels the slot (implicit default pays
        # no accounting) and moves nothing (sticky affinity)
        reg.remove_tenant("mv")
        assert svc.rebalance_tenants() == 0
        assert svc._slots[h.key].tenant is None
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# REST gate + /health tenants block
# ---------------------------------------------------------------------------


@pytest.fixture
def rest_edge(tmp_path):
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from chaos import TrueChain
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    from loadgen import _shim_daemon

    from drand_tpu.http_server import RestServer

    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    chain = TrueChain(n=4)
    daemon = _shim_daemon(chain, head=4)
    daemon.tenancy = reg
    ctrl = AdmissionController(clock=clock, capacity=16,
                               critical_reserve=2, tenancy=reg)
    server = RestServer(daemon, "127.0.0.1:0", admission=ctrl)
    server.start()
    yield reg, server, ctrl
    server.stop()


def _rest_get(server, path):
    url = f"http://127.0.0.1:{server.port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def test_rest_tenant_gate_and_health_block(rest_edge):
    reg, server, ctrl = rest_edge
    # untenanted chain serves normally
    code, obj, _ = _rest_get(server, "/public/1")
    assert code == 200 and obj["round"] == 1
    # pause the chain's tenant: well-formed 429 with the tenant label
    # and Retry-After, BEFORE any store work
    reg.set_tenant(TenantConfig(name="acme", paused=True,
                                chains=("default",)))
    code, obj, headers = _rest_get(server, "/public/1")
    assert code == 429
    assert obj["tenant"] == "acme" and obj["reason"] == "tenant-paused"
    assert int(headers["Retry-After"]) >= 1
    # /health carries the tenants block
    code, health, _ = _rest_get(server, "/health")
    t = health["tenants"]["tenants"]["acme"]
    assert t["paused"] and t["shed"] >= 1
    # unpause: reads flow again
    reg.set_tenant(TenantConfig(name="acme", chains=("default",)))
    code, obj, _ = _rest_get(server, "/public/1")
    assert code == 200


# ---------------------------------------------------------------------------
# Control plane: tenant add/update/remove without restart
# ---------------------------------------------------------------------------


def test_control_plane_tenant_crud(tmp_path):
    from drand_tpu.core.config import Config
    from drand_tpu.core.daemon import DrandDaemon
    from drand_tpu.net import ControlClient
    from drand_tpu.net import convert
    from drand_tpu.protos import drand_pb2 as pb

    cfg = Config(folder=str(tmp_path / "d"), control_port=0,
                 private_listen="127.0.0.1:0", db_engine="memdb")
    d = DrandDaemon(cfg)
    d.start()
    try:
        cc = ControlClient(d.control.port)
        resp = cc.stub.tenant_set(pb.TenantConfigPacket(
            name="acme", weight=2.0, rate=50.0, burst=10,
            device_budget=0.5, chains=["default"], pin_group=1,
            anti_affinity=True, metadata=convert.metadata()))
        assert [t.name for t in resp.tenants] == ["acme"]
        assert resp.tenants[0].pin_group == 1
        # live in both enforcement planes, no restart
        assert d.tenancy.get("acme").rate == 50.0
        assert d.admission.tenancy is d.tenancy
        assert d.tenancy.tenant_for_chain("default") == "acme"
        # persisted beside the multibeacon layout
        assert os.path.exists(registry_path(cfg.folder))
        # update
        resp = cc.stub.tenant_set(pb.TenantConfigPacket(
            name="acme", weight=1.0, pin_group=-1, chains=["default"]))
        assert resp.tenants[0].pin_group == -1
        assert d.tenancy.get("acme").pin_group is None
        # list + remove
        resp = cc.stub.tenant_list(pb.TenantRequest())
        assert len(resp.tenants) == 1
        resp = cc.stub.tenant_remove(pb.TenantRequest(name="acme"))
        assert len(resp.tenants) == 0
        import grpc
        with pytest.raises(grpc.RpcError) as ei:
            cc.stub.tenant_remove(pb.TenantRequest(name="acme"))
        assert ei.value.code() == grpc.StatusCode.NOT_FOUND
        cc.close()
    finally:
        d.stop()


def test_config_wires_registry_into_planes(tmp_path):
    from drand_tpu.core.config import Config
    cfg = Config(folder=str(tmp_path / "d"), db_engine="memdb")
    reg = cfg.tenancy()
    assert cfg.tenancy() is reg
    assert cfg.admission().tenancy is reg
    svc = cfg.verify_service()
    try:
        assert svc._tenancy is reg
    finally:
        cfg.stop_verify_service()


# ---------------------------------------------------------------------------
# the noisy-neighbor acceptance (tests/chaos.py; smoke: --tenant)
# ---------------------------------------------------------------------------


def test_noisy_neighbor_scenario():
    """ISSUE 15 acceptance: with an aggressor tenant flooding sheddable
    reads and saturating its device-time quota on an expensive chain,
    the victim's partials p99 stays under its period, its per-round
    throughput stays within 20% of the aggressor-free run (same seed),
    over-quota rejections are well-formed and tenant-labelled, never
    silent, and placement keeps the tenants on different groups."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from chaos import NoisyNeighborScenario
    r = NoisyNeighborScenario(seed=42).run()
    assert r.ok, r
    assert r.victim_partials_p99 < r.period
    assert r.throughput_ratio >= 0.8
    assert r.aggro_quota_peak >= 1.0 and r.aggro_quota_sheds > 0
    assert r.sheds_well_formed and r.silent_drops == 0
    assert r.placement["victim"] != r.placement["aggro"]
    # same seed, same verdict (deterministic)
    r2 = NoisyNeighborScenario(seed=42).run()
    assert (r2.victim_rounds, r2.aggro_reads_shed, r2.aggro_reads_served) \
        == (r.victim_rounds, r.aggro_reads_shed, r.aggro_reads_served)


def test_wfq_exempts_implicit_default_tenant(tmp_path):
    """A daemon whose chains have no registry entry resolves every
    request to the implicit default tenant, whose 'share' would be the
    whole pool — WFQ must not turn the pre-tenancy wait behavior into
    an instant shed there (normal still rides out a brief squeeze via
    normal_wait, and its timed-out wait stays the ladder signal)."""
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    reg.set_tenant(TenantConfig(name="someone", chains=("elsewhere",)))
    ctrl = mk_ctrl(reg, clock, capacity=6, critical_reserve=2,
                   normal_wait=2.0)
    limit = ctrl.capacity - ctrl.critical_reserve
    held = [ctrl.admit(CLASS_NORMAL, tenant=DEFAULT_TENANT)
            for _ in range(limit)]
    got = []

    def late():
        got.append(ctrl.admit(CLASS_NORMAL, tenant=DEFAULT_TENANT))

    th = threading.Thread(target=late, daemon=True)
    th.start()
    threading.Event().wait(0.1)
    assert not got and th.is_alive()    # waiting, NOT tenant-share shed
    held.pop().release()
    th.join(timeout=5)
    assert got
    got[0].release()
    for t in held:
        t.release()


def test_empty_registry_costs_no_registry_round_trips(tmp_path):
    """No tenants registered -> the admission hot path never consults
    the registry (has_tenants() is a lock-free bool)."""
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    assert not reg.has_tenants()
    calls = []
    orig = reg.admission_view
    reg.admission_view = lambda t: calls.append(t) or orig(t)
    ctrl = mk_ctrl(reg, clock)
    ctrl.admit(CLASS_SHEDDABLE, tenant=DEFAULT_TENANT).release()
    assert ctrl.check_tenant_read(DEFAULT_TENANT) is None
    assert calls == []
    reg.set_tenant(TenantConfig(name="t"))
    assert reg.has_tenants()
    ctrl.admit(CLASS_SHEDDABLE, tenant="t").release()
    assert calls == ["t"]
    reg.remove_tenant("t")
    assert not reg.has_tenants()


def test_late_chain_registration_relabels_slots(tmp_path, fake_pool):
    """Daemon-restart ordering: verify handles are created by
    start_beacon BEFORE the daemon registers the chain hash — the
    registry's register_chain fires the change listeners, so the
    already-created slot picks up its tenant (device-time accounting
    live) and the tenant's pin is applied."""
    from drand_tpu.crypto.verify_service import VerifyService
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock, window=100.0)
    reg.set_tenant(TenantConfig(name="prem", device_budget=1.0,
                                chains=("pchain",), pin_group=3))
    svc = VerifyService(clock=clock, pad=8, background_window=0.0,
                        pool=fake_pool)
    svc.set_tenancy(reg)
    reg.on_change(svc.rebalance_tenants)    # the Config wiring

    def factory(group):
        class B:
            kind = "stub"

            def verify_batch(self, rounds, sigs, prev_sigs=None):
                clock.advance(0.5)
                return np.ones(len(rounds), dtype=bool)
        return B()

    try:
        # handle created BEFORE the chain is indexed (restart order)
        h = svc.handle(SCHEME, pk(9), backend_factory=factory)
        assert svc._slots[h.key].tenant in (None, DEFAULT_TENANT)
        # the daemon registers the chain -> listeners relabel + pin
        reg.register_chain("pchain", pk=pk(9))
        slot = svc._slots[h.key]
        assert slot.tenant == "prem"
        assert slot.gid == 3 and fake_pool.gid_of(h.key) == 3
        # device time now lands on the tenant's ledger
        h.verify_batch([1, 2], [b"x"] * 2)
        assert reg.device_seconds("prem") == pytest.approx(0.5)
        # re-registering the same mapping is a no-op (no churn)
        moves = svc.stats()["tenant_rebalances"]
        reg.register_chain("pchain", pk=pk(9))
        assert svc.stats()["tenant_rebalances"] == moves
    finally:
        svc.stop()


def test_rest_tickets_count_toward_wfq_share(tmp_path):
    """REST admits pre-parse with tenant=None; once the route resolves
    the chain, the held ticket is ATTRIBUTED to the tenant so weighted
    fair queuing sees REST holdings — with the pool contended, the
    flooding tenant's next read sheds tenant-share at the gate while a
    compliant tenant's read passes."""
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    reg.set_tenant(TenantConfig(name="hog", weight=1.0, chains=("hc",)))
    reg.set_tenant(TenantConfig(name="fair", weight=1.0, chains=("fc",)))
    ctrl = mk_ctrl(reg, clock, capacity=6, critical_reserve=2)
    limit = ctrl.capacity - ctrl.critical_reserve
    # the flood: pre-parse (untenanted) tickets filling the pool, each
    # attributed to the hog when its route resolved
    held = []
    for _ in range(limit):
        t = ctrl.admit(CLASS_SHEDDABLE)         # tenant unknown pre-parse
        ctrl.attribute(t, "hog")
        held.append(t)
    assert ctrl.snapshot()["tenant_inflight"] == {"hog": limit}
    # pool full + hog over its share -> its gate check sheds with the
    # tenant label; the compliant tenant's gate stays open
    shed = ctrl.check_tenant_read("hog")
    assert shed is not None and shed.reason == REASON_TENANT_SHARE
    assert shed.tenant == "hog"
    assert ctrl.check_tenant_read("fair") is None
    # attribution is once-only and release unwinds the ledger
    ctrl.attribute(held[0], "fair")             # no-op: already labelled
    assert ctrl.snapshot()["tenant_inflight"] == {"hog": limit}
    for t in held:
        t.release()
    assert ctrl.snapshot()["tenant_inflight"] == {}
    # uncontended pool: holding a share is fine, nothing sheds
    t = ctrl.admit(CLASS_SHEDDABLE)
    ctrl.attribute(t, "hog")
    assert ctrl.check_tenant_read("hog") is None
    t.release()


def test_quota_gauge_tracks_window_drain(tmp_path):
    """The tenant_quota_level gauge must follow the rolling window down
    when a tenant goes idle — admission_view and snapshot() both refresh
    it, so dashboards agree with /health."""
    from drand_tpu.metrics import tenant_quota_level
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock, window=10.0)
    reg.set_tenant(TenantConfig(name="spiky", device_budget=0.1))
    reg.account_device_time("spiky", 5.0)       # 5x the window budget
    gauge = tenant_quota_level.labels("spiky")
    assert gauge._value.get() >= 1.0
    clock.advance(11.0)                         # window drains, no traffic
    reg.snapshot()                              # a /health scrape
    assert gauge._value.get() == 0.0
    reg.account_device_time("spiky", 5.0)
    clock.advance(11.0)
    reg.admission_view("spiky")                 # an admission consult
    assert gauge._value.get() == 0.0


def test_unregistered_chain_slot_stays_unlabelled(tmp_path, fake_pool):
    """A chain resolving to the implicit default gets tenant=None on its
    slot: no per-dispatch registry accounting, no tenant_* series — the
    placement mirror of the admission plane's has_tenants early-out."""
    from drand_tpu.crypto.verify_service import VerifyService
    clock = FakeClock(1000.0)
    reg = mk_registry(tmp_path, clock)
    reg.set_tenant(TenantConfig(name="someone", chains=("elsewhere",)))
    svc = VerifyService(clock=clock, pad=8, background_window=0.0,
                        pool=fake_pool)
    svc.set_tenancy(reg)

    class B:
        kind = "stub"

        def verify_batch(self, rounds, sigs, prev_sigs=None):
            clock.advance(0.25)
            return np.ones(len(rounds), dtype=bool)

    try:
        h = svc.handle(SCHEME, pk(11), backend=B())
        assert svc._slots[h.key].tenant is None
        h.verify_batch([1], [b"x"])
        assert reg.device_seconds(DEFAULT_TENANT) == 0.0
        assert svc.stats()["tenant_map"] == {}
    finally:
        svc.stop()
