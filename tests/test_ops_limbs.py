"""Device limb engine + field tower vs the host golden reference.

Everything is exercised under `jax.jit` — the only supported usage mode (the
loop bodies close over operand tensors, so eager calls would recompile per
call; production code jits whole pipelines).
"""

import random

import jax
import numpy as np
import pytest

from drand_tpu.crypto.host import field as HF
from drand_tpu.crypto.host.params import P
from drand_tpu.ops import limbs as L
from drand_tpu.ops import tower as T

random.seed(1234)


def rint():
    return random.randrange(P)


def rfp2():
    return (rint(), rint())


def rfp12():
    return (tuple(rfp2() for _ in range(3)), tuple(rfp2() for _ in range(3)))


# -- limb engine -------------------------------------------------------------

mont_mul_j = jax.jit(L.mont_mul)
add_mod_j = jax.jit(L.add_mod)
sub_mod_j = jax.jit(L.sub_mod)
neg_mod_j = jax.jit(L.neg_mod)
inv_mod_j = jax.jit(L.inv_mod)


class TestLimbs:
    def test_roundtrip(self):
        xs = [0, 1, P - 1, rint(), rint()]
        for x in xs:
            assert L.limbs_to_int(L.int_to_limbs(x)) == x

    def test_mont_mul_batch(self):
        xs = [rint() for _ in range(16)] + [0, 1, P - 1]
        ys = [rint() for _ in range(16)] + [P - 1, 1, P - 1]
        a, b = L.encode_mont(xs), L.encode_mont(ys)
        got = L.decode_mont(mont_mul_j(a, b))
        assert got == [x * y % P for x, y in zip(xs, ys)]

    def test_add_sub_neg(self):
        xs = [rint() for _ in range(8)] + [0, P - 1]
        ys = [rint() for _ in range(8)] + [0, P - 1]
        a, b = L.encode_mont(xs), L.encode_mont(ys)
        assert L.decode_mont(add_mod_j(a, b)) == [(x + y) % P for x, y in zip(xs, ys)]
        assert L.decode_mont(sub_mod_j(a, b)) == [(x - y) % P for x, y in zip(xs, ys)]
        assert L.decode_mont(neg_mod_j(a)) == [(P - x) % P for x in xs]

    def test_inv(self):
        xs = [rint() for _ in range(4)] + [1, P - 1]
        a = L.encode_mont(xs)
        assert L.decode_mont(inv_mod_j(a)) == [pow(x, P - 2, P) for x in xs]

    def test_inv_zero_is_zero(self):
        assert L.decode_mont(inv_mod_j(L.encode_mont(0))) == 0

    def test_pow_fixed(self):
        e = 0xD201000000010000
        xs = [rint() for _ in range(4)]
        a = L.encode_mont(xs)
        got = L.decode_mont(jax.jit(lambda v: L.pow_fixed(v, e))(a))
        assert got == [pow(x, e, P) for x in xs]


# -- tower -------------------------------------------------------------------

fp2_mul_j = jax.jit(T.fp2_mul)
fp2_sqr_j = jax.jit(T.fp2_sqr)
fp2_inv_j = jax.jit(T.fp2_inv)
fp12_mul_j = jax.jit(T.fp12_mul)
fp12_sqr_j = jax.jit(T.fp12_sqr)
fp12_inv_j = jax.jit(T.fp12_inv)
frob_j = jax.jit(T.fp12_frobenius, static_argnums=1)


class TestTower:
    def test_fp2(self):
        for _ in range(3):
            x, y = rfp2(), rfp2()
            a, b = T.encode_fp2(x), T.encode_fp2(y)
            assert T.decode_fp2(fp2_mul_j(a, b)) == HF.fp2_mul(x, y)
            assert T.decode_fp2(fp2_sqr_j(a)) == HF.fp2_sqr(x)
            assert T.decode_fp2(fp2_inv_j(a)) == HF.fp2_inv(x)

    def test_fp2_xi_conj(self):
        x = rfp2()
        a = T.encode_fp2(x)
        assert T.decode_fp2(jax.jit(T.fp2_mul_xi)(a)) == HF.fp2_mul_xi(x)
        assert T.decode_fp2(jax.jit(T.fp2_conj)(a)) == HF.fp2_conj(x)

    def test_fp12(self):
        x, y = rfp12(), rfp12()
        a, b = T.encode_fp12(x), T.encode_fp12(y)
        assert T.decode_fp12(fp12_mul_j(a, b)) == HF.fp12_mul(x, y)
        assert T.decode_fp12(fp12_sqr_j(a)) == HF.fp12_sqr(x)
        assert T.decode_fp12(fp12_inv_j(a)) == HF.fp12_inv(x)

    def test_frobenius(self):
        x = rfp12()
        a = T.encode_fp12(x)
        for j in (1, 2, 3):
            assert T.decode_fp12(frob_j(a, j)) == HF.fp12_frobenius(x, j)

    def test_is_one(self):
        assert bool(jax.jit(T.fp12_is_one)(T.fp12_ones()))
        assert not bool(jax.jit(T.fp12_is_one)(T.encode_fp12(rfp12())))
