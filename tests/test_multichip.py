"""Multi-device sharding: the limb engine under an 8-device mesh.

Runs on the 8 virtual CPU devices forced by conftest.py.  The heavyweight
sharded program (Lagrange recovery + verification over a ('round','signer')
mesh) lives in __graft_entry__.dryrun_multichip, which the driver executes;
this test keeps a cheap in-suite guarantee that the field kernels compute
identically under sharding.
"""

import secrets

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from drand_tpu.crypto.host.params import P as FP_P
from drand_tpu.ops import limbs as L


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8 virtual devices from conftest")
    return Mesh(np.array(devs[:8]), ("round",))


def test_sharded_mont_mul_matches_host(mesh):
    n = 16  # 2 residues per device
    xs = [secrets.randbelow(FP_P) for _ in range(n)]
    ys = [secrets.randbelow(FP_P) for _ in range(n)]
    sh = NamedSharding(mesh, P("round"))
    f = jax.jit(L.mont_mul, in_shardings=(sh, sh), out_shardings=sh)
    got = L.decode_mont(f(L.encode_mont(xs), L.encode_mont(ys)))
    assert got == [x * y % FP_P for x, y in zip(xs, ys)]


def test_sharded_mont_mul_uses_all_devices(mesh):
    sh = NamedSharding(mesh, P("round"))
    a = jax.device_put(L.encode_mont([1] * 8), sh)
    assert len({s.device for s in a.addressable_shards}) == 8


def test_sharded_verify_batch(mesh):
    """verify_batch shards its round axis over the mesh transparently and
    still localizes a corrupted round (the DP/SP axis of SURVEY.md §5.7)."""
    from drand_tpu.crypto import batch, schemes

    sch = schemes.scheme_from_name(schemes.SHORT_SIG_SCHEME_ID)
    sec, pub = sch.keypair(seed=b"mc-verify")
    ver = batch.BatchBeaconVerifier(sch, sch.public_bytes(pub))
    ver.SHARD_MIN_PAD = 8      # force the sharded path at test width
    n = 8
    rounds = list(range(1, n + 1))
    msgs = [sch.digest_beacon(r, None) for r in rounds]
    sigs = [sch.sign(sec, m) for m in msgs]
    ok = ver.verify_batch(rounds, sigs)
    assert ok.all()
    # corrupt two rounds: swapped signatures verify for the wrong messages
    sigs[3], sigs[4] = sigs[4], sigs[3]
    ok = ver.verify_batch(rounds, sigs)
    assert not ok[3] and not ok[4]
    assert ok[[0, 1, 2, 5, 6, 7]].all()


def test_dryrun_multichip_executes(mesh):
    """Run the driver-graded sharded aggregation step itself (VERDICT r2 #1:
    the one program with no suite coverage is the one the driver grades).
    Any drift in the batch/curve API surface it uses fails here first.

    The dryrun deliberately pins process-global state for the driver
    (canonical XLA_FLAGS, the main /tmp compile-cache dir) — scope the
    pollution so later suite compiles keep the conftest cache config."""
    import os

    import __graft_entry__

    old_flags = os.environ.get("XLA_FLAGS")
    old_dir = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        __graft_entry__.dryrun_multichip(8)
    finally:
        if old_flags is None:
            os.environ.pop("XLA_FLAGS", None)
        else:
            os.environ["XLA_FLAGS"] = old_flags
        jax.config.update("jax_compilation_cache_dir", old_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          old_min)


def test_entry_signature_matches_example_args():
    """entry()'s example_args must stay call-compatible with the returned fn
    (the r2 regression: the fn's signature changed under the entry point)."""
    import inspect

    import __graft_entry__

    fn, example_args = __graft_entry__.entry()
    sig = inspect.signature(fn)
    sig.bind(*example_args)          # raises TypeError on drift
    ok = np.asarray(jax.jit(fn)(*example_args))
    assert ok.all()
