"""Multi-device sharding: the limb engine under an 8-device mesh.

Runs on the 8 virtual CPU devices forced by conftest.py.  The heavyweight
sharded program (Lagrange recovery + verification over a ('round','signer')
mesh) lives in __graft_entry__.dryrun_multichip, which the driver executes;
this test keeps a cheap in-suite guarantee that the field kernels compute
identically under sharding.
"""

import secrets

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from drand_tpu.crypto.host.params import P as FP_P
from drand_tpu.ops import limbs as L


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8 virtual devices from conftest")
    return Mesh(np.array(devs[:8]), ("round",))


def test_sharded_mont_mul_matches_host(mesh):
    n = 16  # 2 residues per device
    xs = [secrets.randbelow(FP_P) for _ in range(n)]
    ys = [secrets.randbelow(FP_P) for _ in range(n)]
    sh = NamedSharding(mesh, P("round"))
    f = jax.jit(L.mont_mul, in_shardings=(sh, sh), out_shardings=sh)
    got = L.decode_mont(f(L.encode_mont(xs), L.encode_mont(ys)))
    assert got == [x * y % FP_P for x, y in zip(xs, ys)]


def test_sharded_mont_mul_uses_all_devices(mesh):
    sh = NamedSharding(mesh, P("round"))
    a = jax.device_put(L.encode_mont([1] * 8), sh)
    assert len({s.device for s in a.addressable_shards}) == 8
