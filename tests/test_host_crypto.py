"""Host crypto known-answer + roundtrip tests.

Mainnet vectors are the public League-of-Entropy beacons the reference pins in
crypto/schemes_test.go:81-130 (rounds 2634945 & 3361396 chained, 7601003
unchained, 3 on the G1 scheme).
"""

import hashlib

import pytest

from drand_tpu.crypto.host import params
from drand_tpu.crypto.host import field as F
from drand_tpu.crypto.host.curve import G1, G2
from drand_tpu.crypto.host.pairing import pairing, pairing_check
from drand_tpu.crypto.host.serialize import (
    g1_from_bytes, g1_to_bytes, g2_from_bytes, g2_to_bytes,
)
from drand_tpu.crypto import tbls
from drand_tpu.crypto.schemes import (
    scheme_from_name, list_schemes, randomness_from_signature,
    get_scheme_by_id_with_default, DEFAULT_SCHEME_ID,
)

MAINNET_BEACONS = [
    # (scheme, round, pubkey, sig, prev_sig)
    ("pedersen-bls-chained", 2634945,
     "868f005eb8e6e4ca0a47c8a77ceaa5309a47978a7c71bc5cce96366b5d7a569937c529eeda66c7293784a9402801af31",
     "814778ed1e480406beb43b74af71ce2f0373e0ea1bfdfea8f9ed62c876c20fcbc7f0163860e3da42ed2148756015f4551451898ffe06d384b4d002245025571b6b7a752f7158b40ad92b13b6d703ad31922a617f2c7f6d960b84d56cf1d79eef",
     "8bd96294383b4d1e04e736360bd7a487f9f409f1e7bd800b720656a310d577b3bdb1e1631af6c5782a1d8979c502f395036181eff4058960fc40bb7034cdae1991d3eda518ab204a077d2f7e724974cf87b407e549bd815cf0b8e5a3832f675d"),
    ("pedersen-bls-chained", 3361396,
     "922a2e93828ff83345bae533f5172669a26c02dc76d6bf59c80892e12ab1455c229211886f35bb56af6d5bea981024df",
     "9904b4ec42e82cb42ad53f171cf0510a5eedff8b5e02e2db5a187489f7875307746998b9a6cf82130d291126d4b83cea1048c9b3f07a067e632c20391dc059d22d6a8e835f3980c8bd0183fb6df00a8fbbe6b8c9f61e888dfa76e12af4d4e355",
     "a2377f4e0403f0fd05f709a3292be1b2b59fe990a673ad7b7561b5bd5982b882a2378d36e39befb6ea3bb7aac113c50a18fb07aa4f9a59f95f1aaa7826dafbfcdbf22347c29996c294286fd11b402ad83edd83fa21fe6735fccb65785edbed47"),
    ("pedersen-bls-unchained", 7601003,
     "8200fc249deb0148eb918d6e213980c5d01acd7fc251900d9260136da3b54836ce125172399ddc69c4e3e11429b62c11",
     "af7eac5897b72401c0f248a26b612c5ef68e0ff830b4d78927988c89b5db3e997bfcdb7c24cb19f549830cd02cb854a1143fd53a1d4e0713ded471260869439060d170a77187eb6371742840e43eccfa225657c4cc2d9619f7c3d680470c9743",
     None),
    ("bls-unchained-on-g1", 3,
     "876f6fa8073736e22f6ff4badaab35c637503718f7a452d178ce69c45d2d8129a54ad2f988ab10c9666f87ab603c59bf013409a5b500555da31720f8eec294d9809b8796f40d5372c71a44ca61226f1eb978310392f98074a608747f77e66c5a",
     "ac7c3ca14bc88bd014260f22dc016b4fe586f9313c3a549c83d195811a99a5d2d4999d4df6daec73ff51fafadd6d5bb5",
     None),
]


def test_params_validate():
    params.validate()
    # final-exp hard-part identity used by pairing.py
    x, p, r = params.X, params.P, params.R
    assert ((x - 1) ** 2 * (x + p) * (x ** 2 + p ** 2 - 1) + 3) == 3 * ((p ** 4 - p ** 2 + 1) // r)


def test_generator_orders():
    assert G1.mul(G1.gen, params.R) is None
    assert G2.mul(G2.gen, params.R) is None


def test_pairing_bilinearity():
    a, b = 987654321, 123456789
    e_ab = pairing(G1.mul(G1.gen, a), G2.mul(G2.gen, b))
    e_ba = pairing(G1.mul(G1.gen, b), G2.mul(G2.gen, a))
    assert e_ab == e_ba
    assert e_ab == F.fp12_pow(pairing(G1.gen, G2.gen), a * b % params.R)
    assert e_ab != F.FP12_ONE


@pytest.mark.parametrize("scheme_id,round_,pub,sig,prev", MAINNET_BEACONS,
                         ids=[f"{b[0]}-r{b[1]}" for b in MAINNET_BEACONS])
def test_mainnet_vectors(scheme_id, round_, pub, sig, prev):
    sch = scheme_from_name(scheme_id)
    prev_b = bytes.fromhex(prev) if prev else None
    assert sch.verify_beacon(bytes.fromhex(pub), round_, prev_b, bytes.fromhex(sig))
    # tampered round must fail
    assert not sch.verify_beacon(bytes.fromhex(pub), round_ + 1, prev_b, bytes.fromhex(sig))


def test_serialization_roundtrip():
    for k in (1, 7, 12345, params.R - 2):
        p1 = G1.mul(G1.gen, k)
        assert g1_from_bytes(g1_to_bytes(p1)) == p1
        p2 = G2.mul(G2.gen, k)
        assert g2_from_bytes(g2_to_bytes(p2)) == p2
    assert g1_from_bytes(g1_to_bytes(None)) is None
    assert g2_from_bytes(g2_to_bytes(None)) is None


def test_serialization_rejects_bad_points():
    # x not on curve
    bad = bytearray(g1_to_bytes(G1.gen))
    bad[47] ^= 1
    with pytest.raises(ValueError):
        g1_from_bytes(bytes(bad))


@pytest.mark.parametrize("scheme_id", list_schemes())
def test_sign_verify_roundtrip(scheme_id):
    sch = scheme_from_name(scheme_id)
    sk, pk = sch.keypair(seed=b"unit-test-seed")
    msg = sch.digest_beacon(42, b"prev-sig-bytes" if sch.chained else None)
    sig = sch.sign(sk, msg)
    assert len(sig) == sch.sig_group.point_len
    assert sch.verify(pk, msg, sig)
    assert not sch.verify(pk, msg + b"x", sig)
    # pub roundtrip through bytes
    assert sch.verify_beacon(sch.public_bytes(pk), 42,
                             b"prev-sig-bytes" if sch.chained else None, sig)


def test_randomness_from_signature():
    sig = b"\x01" * 96
    assert randomness_from_signature(sig) == hashlib.sha256(sig).digest()


def test_default_scheme():
    assert get_scheme_by_id_with_default("").id == DEFAULT_SCHEME_ID


@pytest.mark.parametrize("scheme_id", list_schemes())
def test_tbls_roundtrip(scheme_id):
    sch = scheme_from_name(scheme_id)
    t, n = 3, 5
    poly = tbls.PriPoly.random(t, secret=123456789)
    shares = poly.shares(n)
    pub_poly = poly.commit(sch.key_group)
    msg = sch.digest_beacon(7, None)

    partials = [tbls.sign_partial(sch, s, msg) for s in shares]
    for p in partials:
        assert tbls.verify_partial(sch, pub_poly, msg, p)
    # corrupt partial fails
    bad = bytearray(partials[0])
    bad[0] ^= 1  # wrong index
    assert not tbls.verify_partial(sch, pub_poly, msg, bytes(bad))

    # recovery from any t partials gives the same signature as the secret key
    expected = sch.sign(poly.secret(), msg)
    for subset in ([0, 1, 2], [2, 3, 4], [4, 0, 2]):
        sig = tbls.recover(sch, pub_poly, msg, [partials[i] for i in subset], t, n)
        assert sig == expected
    assert tbls.verify_recovered(sch, pub_poly.public_key(), msg, expected)

    with pytest.raises(ValueError):
        tbls.recover(sch, pub_poly, msg, partials[:t - 1], t, n)
