"""Identity plane (net/identity.py + core/authz.py; ISSUE 19).

Tier-1 coverage: the tenant-token caveat matrix (expiry + skew, chain
allowlist, tampered HMAC chain, revocation through the cache, unknown
caveats fail closed, torn-ledger fail-closed), cert provisioning +
hot-reload + the expiry-grace state machine on a FakeClock, the
SAN <-> roster Handel binding for DNS-named rosters (the PR 15
`sender_binding_enforceable` carve-out, now enforced), and the
anonymous-read byte-identity guarantee (an untenanted daemon never
grows identity state).  The live mTLS fleet legs run in
tests/chaos.py's StolenIdentityScenario and tools/fleet.py --mtls."""

import os
import shutil

import pytest

from drand_tpu.beacon import FakeClock
from drand_tpu.beacon import handel as H
from drand_tpu.core.authz import (REASON_BAD_SIGNATURE, REASON_EXPIRED,
                                  REASON_MALFORMED, REASON_REVOKED,
                                  REASON_UNKNOWN, REASON_WRONG_CHAIN,
                                  TokenAuthority, _b64u, _chain_sig,
                                  bearer_token, grpc_bearer)
from drand_tpu.crypto.schemes import scheme_from_name
from drand_tpu.net import identity as ident


def mk_authority(tmp_path, clock=None, **kw):
    return TokenAuthority(str(tmp_path / "multibeacon"),
                          clock=clock or FakeClock(1000.0), **kw)


def _partial(idx, body=b"-good"):
    return idx.to_bytes(2, "big") + body


class StubVerifier:
    def verify(self, msg, partials):
        return [p.endswith(b"-good") for p in partials]


# ---------------------------------------------------------------------------
# token caveat matrix
# ---------------------------------------------------------------------------


def test_token_mint_verify_roundtrip(tmp_path):
    clock = FakeClock(1000.0)
    auth = mk_authority(tmp_path, clock)
    token, rec = auth.mint("acme", chains=("default", "c2"),
                           ttl=600.0, read_only=True)
    v = auth.verify(token)
    assert v.ok and v.tenant == "acme" and v.read_only
    assert v.chains == ("default", "c2")
    assert v.expires == 1600.0
    assert v.token_id == rec.token_id
    # chain allowlist: listed chains pass, others are wrong-chain
    assert auth.verify(token, chain="default").ok
    assert auth.verify(token, chain="c2").ok
    bad = auth.verify(token, chain="other")
    assert not bad.ok and bad.reason == REASON_WRONG_CHAIN
    # an unrestricted token (empty chains caveat) serves any chain
    tok2, _ = auth.mint("acme")
    assert auth.verify(tok2, chain="anything").ok


def test_token_expiry_honors_skew_boundary(tmp_path):
    clock = FakeClock(1000.0)
    auth = mk_authority(tmp_path, clock, skew=30.0)
    token, _ = auth.mint("acme", ttl=100.0)       # expires at 1100
    clock.set_time(1100.0 + 30.0)                 # exactly expiry + skew
    assert auth.verify(token).ok, "inside the skew window must pass"
    clock.advance(1.0)
    v = auth.verify(token)
    assert not v.ok and v.reason == REASON_EXPIRED
    # no-expiry tokens never age out
    forever, _ = auth.mint("acme")
    clock.advance(10 ** 9)
    assert auth.verify(forever).ok


def test_token_tampering_breaks_the_hmac_chain(tmp_path):
    auth = mk_authority(tmp_path)
    token, _ = auth.mint("acme", read_only=True)
    parts = token.split(".")
    # rewrite the ro=1 caveat to ro=0 without re-signing
    ro_idx = next(i for i, p in enumerate(parts[2:-1], start=2)
                  if p == _b64u(b"ro=1"))
    parts[ro_idx] = _b64u(b"ro=0")
    v = auth.verify(".".join(parts))
    assert not v.ok and v.reason == REASON_BAD_SIGNATURE
    # reordering caveats breaks it too (order is part of the chain)
    parts = token.split(".")
    parts[2], parts[3] = parts[3], parts[2]
    assert auth.verify(".".join(parts)).reason == REASON_BAD_SIGNATURE
    # and a flipped signature byte
    parts = token.split(".")
    parts[-1] = ("0" if parts[-1][0] != "0" else "1") + parts[-1][1:]
    assert auth.verify(".".join(parts)).reason == REASON_BAD_SIGNATURE


def test_token_malformed_inputs_rejected(tmp_path):
    auth = mk_authority(tmp_path)
    auth.mint("acme")          # ensure a root key exists
    for junk in ("", "garbage", "dt1.only-two", "dt2.x.y.z",
                 "dt1." + "x" * 5000, None, 42):
        v = auth.verify(junk)
        assert not v.ok and v.reason == REASON_MALFORMED


def test_token_unknown_caveat_fails_closed(tmp_path):
    """A correctly-SIGNED token carrying a caveat this build does not
    understand is rejected: honoring it as a no-op would widen the
    token's authority."""
    auth = mk_authority(tmp_path)
    auth.mint("acme")
    key = auth._root_key
    caveats = ("t=acme", "c=", "e=0", "ro=0", "x=later-feature")
    sig = _chain_sig(key, "cafe0123", caveats)
    token = ".".join(("dt1", "cafe0123")
                     + tuple(_b64u(c.encode()) for c in caveats)
                     + (sig.hex(),))
    v = auth.verify(token)
    assert not v.ok and v.reason == REASON_MALFORMED


def test_token_revocation_pierces_the_cache(tmp_path):
    auth = mk_authority(tmp_path)
    token, rec = auth.mint("acme")
    assert auth.verify(token).ok          # primes the structural cache
    assert auth.revoke(rec.token_id)
    v = auth.verify(token)
    assert not v.ok and v.reason == REASON_REVOKED
    assert not auth.revoke("no-such-id")
    # revocation survives a restart (ledger persisted atomically)
    auth2 = TokenAuthority(auth.folder, clock=FakeClock(1000.0))
    assert auth2.verify(token).reason == REASON_REVOKED


def test_token_torn_ledger_fails_closed(tmp_path):
    """Key survives but the ledger is torn/lost: tokens still verify
    structurally, but without a record they are UNKNOWN — a crash must
    never resurrect a revoked token."""
    auth = mk_authority(tmp_path)
    token, _ = auth.mint("acme")
    os.unlink(os.path.join(auth.folder, "tokens.json"))
    auth2 = TokenAuthority(auth.folder, clock=FakeClock(1000.0))
    v = auth2.verify(token)
    assert not v.ok and v.reason == REASON_UNKNOWN


def test_token_foreign_key_rejected(tmp_path):
    """A token minted under another daemon's root key fails the
    signature check here."""
    theirs = TokenAuthority(str(tmp_path / "theirs"), clock=FakeClock(0))
    ours = TokenAuthority(str(tmp_path / "ours"), clock=FakeClock(0))
    token, _ = theirs.mint("acme")
    ours.mint("other")          # give ours a (different) root key
    assert ours.verify(token).reason == REASON_BAD_SIGNATURE


def test_token_persistence_across_restart(tmp_path):
    auth = mk_authority(tmp_path)
    token, rec = auth.mint("acme", chains=("default",), ttl=500.0)
    auth2 = TokenAuthority(auth.folder, clock=FakeClock(1000.0))
    assert auth2.active()
    v = auth2.verify(token, chain="default")
    assert v.ok and v.tenant == "acme"
    assert [r.token_id for r in auth2.tokens()] == \
        [r.token_id for r in auth.tokens()]
    key_mode = os.stat(os.path.join(auth.folder, "tokens.key")).st_mode
    assert key_mode & 0o077 == 0, "root key must not be group/world readable"


def test_bearer_extraction_helpers():
    assert bearer_token(None) is None
    assert bearer_token("") is None
    assert bearer_token("Bearer abc.def") == "abc.def"
    assert bearer_token("bearer abc") == "abc"
    assert bearer_token("abc") == "abc"
    assert grpc_bearer(None) is None
    assert grpc_bearer([("x-other", "1")]) is None
    assert grpc_bearer([("authorization", "Bearer tok")]) == "tok"


# ---------------------------------------------------------------------------
# anonymous-read byte-identity: no tokens ever minted => the authz plane
# is inert — no files, no active() flag, no state growth on probes
# ---------------------------------------------------------------------------


def test_untenanted_authority_stays_inert(tmp_path):
    folder = tmp_path / "multibeacon"
    auth = TokenAuthority(str(folder), clock=FakeClock(0))
    assert not auth.active()
    # probing with garbage (or even well-formed foreign tokens) creates
    # no files and flips no state
    assert not auth.verify("dt1.aa.dD0x.deadbeef").ok
    assert not auth.verify("garbage").ok
    assert not auth.active()
    assert not folder.exists(), "verification must never create files"
    assert auth.tokens() == []


def test_config_without_identity_dir_builds_no_plane(tmp_path):
    from drand_tpu.core.config import Config
    cfg = Config(folder=str(tmp_path))
    assert cfg.identity() is None
    assert not cfg.authority().active()


# ---------------------------------------------------------------------------
# cert provisioning + IdentityPlane state machine (openssl CLI)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("identity"))
    dirs = ident.provision_fleet(
        root, {"node-a": ["node-a.example"], "node-b": ["10.0.0.2"]},
        days=3)
    return root, dirs


def test_provision_fleet_sans_carry_roster_and_loopback(fleet):
    root, dirs = fleet
    facts = ident.cert_facts(os.path.join(dirs["node-a"], "node.crt"))
    assert "node-a.example" in facts["names"]
    assert "127.0.0.1" in facts["names"] and "localhost" in facts["names"]
    assert facts["common_name"] == "node-a"
    assert facts["not_after"] is not None
    # issue_cert (unlike provision_fleet) adds NO loopback SANs — the
    # chaos scenario's attacker cert depends on this
    lone = ident.issue_cert(os.path.join(root, "lone"), "lone",
                            ["attacker.example"],
                            os.path.join(root, "ca"), days=3)
    lf = ident.cert_facts(os.path.join(lone, "node.crt"))
    assert lf["names"] == ("attacker.example",)
    # private keys land 0600
    mode = os.stat(os.path.join(dirs["node-a"], "node.key")).st_mode
    assert mode & 0o077 == 0


def test_identity_plane_expiry_grace_state_machine(fleet, tmp_path):
    root, dirs = fleet
    cert_dir = str(tmp_path / "certs")
    shutil.copytree(dirs["node-a"], cert_dir)
    not_after = ident.cert_facts(
        os.path.join(cert_dir, "node.crt"))["not_after"]
    clock = FakeClock(not_after - 1000.0)
    plane = ident.IdentityPlane(cert_dir, clock=clock,
                                reload_interval=5.0, expiry_grace=3600.0)
    assert plane.state() == ident.STATE_FRESH
    clock.set_time(not_after + 1.0)
    assert plane.state() == ident.STATE_GRACE
    clock.set_time(not_after + 3600.0 + 1.0)
    assert plane.state() == ident.STATE_EXPIRED
    # degraded NEVER means bricked: both credential surfaces still serve
    assert plane.server_credentials() is not None
    assert plane.channel_credentials() is not None
    st = plane.status()
    assert st["state"] == ident.STATE_EXPIRED and st["epoch"] == 0


def test_identity_plane_hot_reload_bumps_epoch(fleet, tmp_path):
    root, dirs = fleet
    cert_dir = str(tmp_path / "certs")
    shutil.copytree(dirs["node-a"], cert_dir)
    clock = FakeClock(1000.0)
    plane = ident.IdentityPlane(cert_dir, clock=clock, reload_interval=5.0)
    assert plane.epoch == 0
    creds0 = plane.channel_credentials()
    plane.maybe_reload()        # arm the rate-limit window
    # rotate: reissue into the same dir (new key + crt, new SAN set)
    ident.issue_cert(cert_dir, "node-a", ["node-a.example", "rotated.example"],
                     os.path.join(root, "ca"), days=3)
    # inside the rate-limit window nothing happens...
    assert not plane.maybe_reload()
    assert plane.epoch == 0
    # ...past it (or forced) the new generation swaps in atomically
    clock.advance(6.0)
    assert plane.maybe_reload()
    assert plane.epoch == 1
    assert "rotated.example" in plane.names()
    assert plane.channel_credentials() is not creds0, \
        "rotation must invalidate the cached channel credentials"
    assert plane.status()["reloads"] == 1


def test_identity_plane_torn_rotation_keeps_last_good(fleet, tmp_path):
    root, dirs = fleet
    cert_dir = str(tmp_path / "certs")
    shutil.copytree(dirs["node-b"], cert_dir)
    plane = ident.IdentityPlane(cert_dir, clock=FakeClock(1000.0))
    os.unlink(os.path.join(cert_dir, "node.crt"))
    assert not plane.maybe_reload(force=True)
    assert plane.epoch == 0 and plane.channel_credentials() is not None


def test_identity_plane_requires_complete_dir(tmp_path):
    with pytest.raises(ident.IdentityError, match="incomplete"):
        ident.IdentityPlane(str(tmp_path / "empty"))


def test_peer_identity_matching_and_extraction():
    pid = ident.PeerIdentity(names=("Node-A.Example", "10.0.0.2"),
                             common_name="node-a")
    assert pid.matches("node-a.example")          # case-insensitive
    assert pid.matches("10.0.0.2")
    assert pid.matches("node-a")                  # CN fallback
    assert not pid.matches("node-b.example")
    assert not pid.matches("")
    assert pid.label == "node-a"

    class Ctx:
        def __init__(self, auth):
            self._auth = auth

        def auth_context(self):
            return self._auth

    good = Ctx({"transport_security_type": (b"ssl",),
                "x509_subject_alternative_name": (b"node-a.example",),
                "x509_common_name": (b"node-a",)})
    got = ident.peer_identity(good)
    assert got is not None and got.matches("node-a.example")
    assert ident.peer_identity(Ctx({})) is None           # plaintext
    assert ident.peer_identity(Ctx(None)) is None


# ---------------------------------------------------------------------------
# Handel binding: DNS-named rosters are now enforceable via the mTLS
# identity (the sender_binding_enforceable carve-out closes)
# ---------------------------------------------------------------------------


def _dns_coordinator():
    scheme = scheme_from_name("pedersen-bls-chained")
    addrs = {i: f"node-{i}.example.com:443" for i in range(8)}
    c = H.HandelCoordinator(
        group_n=8, me=0, threshold=5, scheme=scheme,
        verifier=StubVerifier(), transport=lambda i, p: None,
        on_complete=lambda r, p, parts: None, clock=FakeClock(0),
        cfg=H.HandelConfig(min_group=2, window=8, bad_limit=3),
        score_key=lambda i: addrs[i], beacon_id="mtls-bind")
    c.submit_own(1, None, _partial(0))
    return c


def _pkt(sender):
    block = H.own_block(8, sender, 2)
    return H.to_packet(1, None, 2, sender,
                       H.Aggregate({i: _partial(i) for i in block}), 8,
                       "mtls-bind")


def test_handel_dns_roster_enforced_with_mtls_identity():
    """With an authenticated PeerIdentity the DNS roster binds: the SAN
    of the sender cert must cover the claimed index's roster host."""
    from drand_tpu.metrics import identity_rejections
    c = _dns_coordinator()
    honest = ident.PeerIdentity(names=("node-3.example.com",),
                                common_name="node-3")
    c.receive(_pkt(3), peer="ipv4:10.9.9.9:41234", auth=honest)
    sess = c._sessions[(1, b"")]
    assert sess._pending, "SAN-matching candidate must enter the session"

    before = identity_rejections.labels("handel",
                                        "impersonation")._value.get()
    attacker = ident.PeerIdentity(names=("attacker.example",),
                                  common_name="attacker")
    with pytest.raises(ValueError, match="authenticated as attacker"):
        c.receive(_pkt(5), peer="ipv4:10.9.9.9:41234", auth=attacker)
    after = identity_rejections.labels("handel",
                                       "impersonation")._value.get()
    assert after == before + 1
    # the forgery never reached the session: the claimed index's
    # demotion counter is untouched (no griefing of honest peers)
    assert sess._bad.get(5, 0) == 0


def test_handel_auth_replaces_ip_heuristic():
    """When `auth` is present it REPLACES the transport-IP heuristic —
    a numeric peer mismatch is irrelevant if the cert SAN matches, and
    vice versa a matching IP cannot rescue a SAN mismatch."""
    c = _dns_coordinator()
    # DNS roster + no auth: heuristic skips (PR 15 behavior preserved)
    c.receive(_pkt(3), peer="ipv4:10.2.3.4:41234")
    assert c._sessions[(1, b"")]._pending


# ---------------------------------------------------------------------------
# the full stolen-identity scenario (live mTLS daemons; chaos_smoke
# --identity runs the same legs in CI)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_stolen_identity_scenario(tmp_path):
    from chaos import StolenIdentityScenario
    r = StolenIdentityScenario(seed=42, root=str(tmp_path)).run()
    assert r.ok, r
    assert r.impersonation_rejected == r.forged_packets
    assert r.token_reasons == {"revoked": "revoked", "expired": "expired",
                               "tampered": "bad-signature"}
