"""Resilience layer (net/resilience.py) + chaos harness (tests/chaos.py).

Unit tests drive the backoff/breaker/deadline primitives with the fake
clock (no real sleeping anywhere); the scenario tests are the PR's
acceptance criteria: a 5-node sync with 2 Byzantine peers converges to one
identical verified chain on all honest nodes, deterministically from the
seed, with breaker transitions visible in the metrics scrape."""

import collections

import pytest

from chaos import (AutoClock, ChaosScenario, ChaosStream, FaultPlan,
                   TrueChain, stable_seed)
from drand_tpu.beacon.clock import FakeClock
from drand_tpu.beacon.sync import ErrFailedAll, SyncManager
from drand_tpu.chain.memdb import MemDBStore
from drand_tpu.core.follow import FollowFacade
from drand_tpu.crypto.hostverify import HostBatchVerifier
from drand_tpu.metrics import scrape
from drand_tpu.net.resilience import (CLOSED, HALF_OPEN, OPEN, BackoffPolicy,
                                      BreakerOpen, BreakerRegistry,
                                      CircuitBreaker, Deadline,
                                      DeadlineExceeded, ResiliencePolicy)

# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def test_backoff_full_jitter_bounded_and_deterministic():
    import random
    pol = BackoffPolicy(base=0.5, factor=2.0, cap=4.0)
    d1 = [pol.delay(a, random.Random(7)) for a in range(8)]
    d2 = [pol.delay(a, random.Random(7)) for a in range(8)]
    assert d1 == d2                       # same rng state, same schedule
    for attempt, d in enumerate(d1):
        assert 0.0 <= d <= min(4.0, 0.5 * 2 ** attempt)
    assert BackoffPolicy(base=1.0, cap=8.0, jitter=False).delay(2) == 4.0


def test_deadline_clamps_and_expires():
    clk = FakeClock(100.0)
    d = Deadline.after(clk, 50.0)
    assert not d.expired
    assert d.clamp(60.0) == pytest.approx(50.0)   # budget < static timeout
    assert d.clamp(10.0) == pytest.approx(10.0)   # static timeout < budget
    clk.advance(49.0)
    assert d.clamp() == pytest.approx(1.0)
    clk.advance(2.0)
    assert d.expired
    with pytest.raises(DeadlineExceeded):
        d.clamp(5.0)


def test_breaker_lifecycle_with_fake_clock():
    clk = FakeClock(0.0)
    br = CircuitBreaker("peer-a", clock=clk, failures=3, cooldown=10.0,
                        scope="unit")
    assert br.state == CLOSED
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED             # below threshold
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED             # success reset the streak
    br.record_failure()
    assert br.state == OPEN
    assert not br.allow()                 # cooldown not elapsed
    clk.advance(10.0)
    assert br.allow()                     # admitted as the half-open probe
    assert br.state == HALF_OPEN
    assert not br.allow()                 # single probe at a time
    br.record_failure()                   # probe failed
    assert br.state == OPEN
    clk.advance(10.0)
    assert br.allow()
    br.record_success()                   # probe succeeded
    assert br.state == CLOSED


def test_breaker_transitions_visible_in_scrape():
    clk = FakeClock(0.0)
    br = CircuitBreaker("peer-scrape", clock=clk, failures=1, cooldown=5.0,
                        scope="scrape-test")
    br.record_failure()
    clk.advance(5.0)
    br.allow()
    text = scrape("group").decode()
    assert ('resilience_breaker_state{address="peer-scrape",'
            'scope="scrape-test"} 2.0') in text
    assert ('resilience_breaker_transitions_total{address="peer-scrape",'
            'scope="scrape-test",state="open"} 1.0') in text
    assert 'state="half_open"' in text


def test_half_open_probe_slot_reclaimed_after_cooldown():
    """A probe whose caller never reports back must not wedge the breaker
    in HALF_OPEN forever."""
    clk = FakeClock(0.0)
    br = CircuitBreaker("p", clock=clk, failures=1, cooldown=10.0,
                        scope="probe-reclaim")
    br.record_failure()               # OPEN at t=0
    clk.advance(10.0)
    assert br.allow()                 # probe admitted... and abandoned
    assert not br.allow()
    clk.advance(10.0)                 # stale: one cooldown with no verdict
    assert br.allow()                 # slot reclaimed, breaker self-healed


def test_expired_deadline_does_not_strand_half_open_probe():
    """DeadlineExceeded must be raised BEFORE breaker admission, or the
    spent-budget call would strand the half-open probe slot."""
    clk = FakeClock(0.0)
    pol = ResiliencePolicy(clock=clk, scope="probe-deadline", seed=6,
                           breakers=BreakerRegistry(clock=clk, failures=1,
                                                    cooldown=10.0,
                                                    scope="probe-deadline"),
                           max_attempts=1)

    def down(timeout):
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        pol.call(down, key="p", op="t")
    clk.advance(10.0)                 # cooldown elapsed: next call probes
    spent = Deadline.after(clk, 0.0)
    with pytest.raises(DeadlineExceeded):
        pol.call(lambda t: "ok", key="p", op="t", deadline=spent)
    # the probe slot was NOT consumed: a budgeted call can still probe
    assert pol.call(lambda t: "ok", key="p", op="t") == "ok"
    assert pol.breaker("p").state == CLOSED


def test_registry_ranks_closed_peers_first():
    clk = FakeClock(0.0)
    reg = BreakerRegistry(clock=clk, failures=1, cooldown=100.0, scope="rank")
    for peer in ("quarantined", "probe_ready"):
        reg.breaker(peer).record_failure()          # both open
    clk.advance(50.0)
    # re-open probe_ready so its cooldown window sits in the past
    reg.breaker("probe_ready").record_success()
    reg.breaker("probe_ready").record_failure()
    clk.advance(60.0)   # quarantined's cooldown (t=100) elapsed,
                        # probe_ready's (t=150) not yet
    assert reg.preference("healthy") == 0           # unknown = closed
    assert reg.preference("quarantined") == 1       # probe-eligible now
    assert reg.preference("probe_ready") == 2       # still cooling down
    import random
    order = reg.rank(["probe_ready", "quarantined", "healthy"],
                     rng=random.Random(1))
    assert order[0] == "healthy"
    assert order[-1] == "probe_ready"


def test_policy_retries_then_succeeds_instantly_on_auto_clock():
    clk = AutoClock(0.0)
    pol = ResiliencePolicy(clock=clk, scope="unit-retry", seed=11)
    calls = []

    def fn(timeout):
        calls.append(timeout)
        if len(calls) < 3:
            raise ConnectionError("flaky")
        return "ok"

    assert pol.call(fn, key="p", op="t", timeout=5.0) == "ok"
    assert len(calls) == 3                # 2 failures + success, no sleeping
    assert pol.breaker("p").state == CLOSED


def test_policy_deadline_bounds_the_retry_chain():
    clk = AutoClock(0.0)
    pol = ResiliencePolicy(clock=clk, scope="unit-deadline", seed=2,
                           backoff=BackoffPolicy(base=10.0, jitter=False,
                                                 cap=10.0),
                           max_attempts=100)
    deadline = Deadline.after(clk, 25.0)
    calls = []

    def fn(timeout):
        calls.append(timeout)
        raise ConnectionError("always down")

    with pytest.raises(ConnectionError):
        pol.call(fn, op="t", timeout=60.0, deadline=deadline)
    # every per-attempt timeout was clamped to the remaining budget
    assert all(t <= 25.0 for t in calls)
    assert len(calls) <= 4                # 10s backoff inside a 25s budget


def test_policy_open_breaker_rejects_without_dialing():
    clk = FakeClock(0.0)
    pol = ResiliencePolicy(clock=clk, scope="unit-open", seed=3,
                           breakers=BreakerRegistry(clock=clk, failures=1,
                                                    cooldown=1000.0,
                                                    scope="unit-open"),
                           max_attempts=1)
    def fn(timeout):
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        pol.call(fn, key="p", op="t")
    assert pol.breaker("p").state == OPEN
    calls = []
    with pytest.raises(BreakerOpen):
        pol.call(lambda t: calls.append(t), key="p", op="t")
    assert calls == []                    # rejected before dialing


def test_force_probe_admits_before_cooldown():
    """The all-quarantined last resort: an OPEN breaker can be forced to
    HALF_OPEN early so the production client's admission check passes."""
    clk = FakeClock(0.0)
    br = CircuitBreaker("p", clock=clk, failures=1, cooldown=1000.0,
                        scope="force-probe")
    br.record_failure()
    assert br.state == OPEN and not br.allow()
    br.force_probe()
    assert br.state == HALF_OPEN
    assert br.allow()                     # probe admitted despite cooldown
    br.record_success()
    assert br.state == CLOSED
    br.force_probe()                      # no-op outside OPEN
    assert br.state == CLOSED


def test_breaker_opened_by_own_failure_surfaces_real_error():
    """When THIS call's failed attempt opens the breaker, the next attempt
    must surface the real transport error, not mask it as BreakerOpen."""
    clk = AutoClock(0.0)
    pol = ResiliencePolicy(clock=clk, scope="unit-mask", seed=5,
                           breakers=BreakerRegistry(clock=clk, failures=1,
                                                    cooldown=1000.0,
                                                    scope="unit-mask"),
                           max_attempts=3)

    def fn(timeout):
        raise ConnectionError("the real reason")

    with pytest.raises(ConnectionError):
        pol.call(fn, key="p", op="t")
    # a FRESH call against the already-open breaker still fast-fails
    with pytest.raises(BreakerOpen):
        pol.call(fn, key="p", op="t")


def test_stable_seed_is_process_independent():
    assert stable_seed(42, "node3") == stable_seed(42, "node3")
    assert stable_seed(42, "node3") != stable_seed(42, "node4")
    # regression pin: builtin hash() would change across processes
    assert stable_seed(1, "x") == 0xCF2AE21A


# ---------------------------------------------------------------------------
# chaos scenarios (the acceptance criteria)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def true_chain():
    return TrueChain(n=24)


def test_five_node_sync_converges_with_two_byzantine(true_chain):
    sc = ChaosScenario(seed=42, n_nodes=5, n_byzantine=2, rounds=24,
                       chain=true_chain)
    result = sc.run()
    assert result.converged
    faults = collections.Counter(f for _, _, _, f in result.events)
    assert faults                         # the Byzantine peers really fired
    text = scrape("group").decode()
    assert "resilience_breaker_transitions_total" in text
    # every honest node holds the true chain
    for addr, store in sc.stores.items():
        for r in (1, 12, 24):
            assert store.get(r).signature == true_chain.beacons[r].signature


def test_chaos_run_is_deterministic_from_the_seed(true_chain):
    r1 = ChaosScenario(seed=1234, chain=true_chain).run()
    r2 = ChaosScenario(seed=1234, chain=true_chain).run()
    assert r1.converged and r2.converged
    assert r1.chain_digest == r2.chain_digest
    r3 = ChaosScenario(seed=77, chain=true_chain).run()
    assert r3.converged
    assert r3.chain_digest == r1.chain_digest   # same TRUE chain either way


def test_crash_restart_peer_recovers_within_budget(true_chain):
    """A peer in its crash window rejects everything; the budgeted sync
    keeps probing (breaker cooldowns advance the auto clock) and succeeds
    once the fake time passes the restart point."""
    clock = AutoClock(1000.0)
    store = MemDBStore(buffer_size=64)
    facade = FollowFacade(store, true_chain.scheme.chained,
                          true_chain.genesis_seed)
    plan = FaultPlan(seed=5, crash_at=0.0, restart_at=1050.0)
    events = []

    def fetch(peer, fr):
        src = (true_chain.beacons[r] for r in range(fr, 25))
        return ChaosStream(src, plan, clock, "flappy", 0, events)

    policy = ResiliencePolicy(
        clock=clock, seed=9, scope="crash-test",
        breakers=BreakerRegistry(clock=clock, failures=1, cooldown=20.0,
                                 scope="crash-test"))
    syncm = SyncManager(
        chain=facade, scheme=true_chain.scheme,
        public_key_bytes=true_chain.public, period=30, clock=clock,
        fetch=fetch, peers=["flappy"], chunk=8,
        verifier=HostBatchVerifier(true_chain.scheme, true_chain.public),
        resilience=policy, sync_budget=500.0)
    syncm.sync(24, ["flappy"])
    assert facade.last().round == 24
    assert any(f == "crash" for _, _, _, f in events)
    assert clock.now() >= 1050.0          # really waited out the crash


def test_budget_spent_raises_err_failed_all(true_chain):
    """ErrFailedAll surfaces only once the sync budget is spent — and the
    breaker state from the failed pass steers the NEXT sync away from the
    bad peer immediately."""
    clock = AutoClock(1000.0)
    store = MemDBStore(buffer_size=64)
    facade = FollowFacade(store, true_chain.scheme.chained,
                          true_chain.genesis_seed)
    always_corrupt = FaultPlan(seed=8, corrupt=1.0)
    streams = {"n": 0}

    def fetch(peer, fr):
        src = (true_chain.beacons[r] for r in range(fr, 25))
        if peer == "byzantine":
            streams["n"] += 1
            return ChaosStream(src, always_corrupt, clock, "byzantine",
                               streams["n"], [])
        return src

    policy = ResiliencePolicy(
        clock=clock, seed=4, scope="budget-test",
        breakers=BreakerRegistry(clock=clock, failures=1, cooldown=10_000.0,
                                 scope="budget-test"))
    syncm = SyncManager(
        chain=facade, scheme=true_chain.scheme,
        public_key_bytes=true_chain.public, period=30, clock=clock,
        fetch=fetch, peers=["byzantine"], chunk=8,
        verifier=HostBatchVerifier(true_chain.scheme, true_chain.public),
        resilience=policy, sync_budget=50.0)
    with pytest.raises(ErrFailedAll):
        syncm.sync(24, ["byzantine"])
    assert policy.breaker("byzantine").state == OPEN
    # failover sync with a healthy peer: quarantined one is skipped
    syncm.sync(24, ["byzantine", "honest"])
    assert facade.last().round == 24


def test_all_quarantined_peers_dialed_as_last_resort(true_chain):
    """When EVERY peer is quarantined, sync() forces a probe instead of
    idling out the cooldown — a healed partition recovers immediately."""
    clock = AutoClock(1000.0)
    store = MemDBStore(buffer_size=64)
    facade = FollowFacade(store, true_chain.scheme.chained,
                          true_chain.genesis_seed)

    def fetch(peer, fr):
        return (true_chain.beacons[r] for r in range(fr, 25))

    policy = ResiliencePolicy(
        clock=clock, seed=12, scope="last-resort",
        breakers=BreakerRegistry(clock=clock, failures=1,
                                 cooldown=100_000.0, scope="last-resort"))
    policy.breaker("only").record_failure()         # quarantined, cooldown
    assert policy.breakers.preference("only") == 2  # nowhere near elapsed
    syncm = SyncManager(
        chain=facade, scheme=true_chain.scheme,
        public_key_bytes=true_chain.public, period=30, clock=clock,
        fetch=fetch, peers=["only"], chunk=8,
        verifier=HostBatchVerifier(true_chain.scheme, true_chain.public),
        resilience=policy, sync_budget=50.0)
    syncm.sync(24, ["only"])                        # no ErrFailedAll
    assert facade.last().round == 24
    assert clock.now() < 1000.0 + 100_000.0         # did NOT wait cooldown


def test_repair_skips_breaker_rejections_and_closes_streams(true_chain):
    """correct_past_beacons: a client-side BreakerOpen is not evidence
    against the peer, and every fetched stream is torn down."""
    store = MemDBStore(buffer_size=64)
    facade = FollowFacade(store, true_chain.scheme.chained,
                          true_chain.genesis_seed)
    closed = []

    class TrackedStream:
        def __init__(self, rounds):
            self._it = iter(true_chain.beacons[r] for r in rounds)

        def __iter__(self):
            return self

        def __next__(self):
            return next(self._it)

        def cancel(self):
            closed.append(True)

    def fetch(peer, fr):
        if peer == "rejected":
            raise BreakerOpen("rejected open")
        return TrackedStream(range(fr, 25))

    clock = AutoClock(0.0)
    policy = ResiliencePolicy(
        clock=clock, seed=3, scope="repair-acct",
        breakers=BreakerRegistry(clock=clock, failures=1,
                                 cooldown=100_000.0, scope="repair-acct"))
    syncm = SyncManager(
        chain=facade, scheme=true_chain.scheme,
        public_key_bytes=true_chain.public, period=30, clock=clock,
        fetch=fetch, peers=["rejected", "honest"], chunk=8,
        verifier=HostBatchVerifier(true_chain.scheme, true_chain.public),
        resilience=policy)
    left = syncm.correct_past_beacons(store, [3, 7],
                                      peers=["rejected", "honest"])
    assert left == []
    # the rejected peer took no strike (would have OPENed at failures=1)
    assert policy.breaker("rejected").state == CLOSED
    assert len(closed) == 2               # one torn-down stream per round


def test_node_missing_partials_catches_up_without_forking():
    """A node that was down while the network advanced (missed partials for
    several rounds) catches up over the sync path and rejoins the round
    loop WITHOUT forking: every stored round matches the live nodes
    byte-for-byte."""
    from harness import BeaconScenario

    sc = BeaconScenario(n=3, thr=2, period=30)
    try:
        sc.start_all()
        sc.advance_to_genesis()
        sc.wait_all(1)
        store2 = sc.kill(2)
        sc.advance_round()
        sc.wait_all(2)                    # rounds 2-3 happen without node 2
        sc.advance_round()
        sc.wait_all(3)
        h2 = sc.restart(2, store2)

        def fetch(peer, from_round):
            st = sc.handlers[0].chain.store
            r = from_round
            while True:
                try:
                    b = st.get(r)
                except Exception:
                    return
                yield b
                r += 1

        syncm = SyncManager(
            chain=h2.chain, scheme=sc.scheme,
            public_key_bytes=sc.public_key, period=30, clock=sc.clock,
            fetch=fetch, peers=["node0"], chunk=8,
            verifier=HostBatchVerifier(sc.scheme, sc.public_key))
        target = sc.handlers[0].chain.last().round
        syncm.sync(target, ["node0"])
        assert h2.chain.last().round >= target
        for r in range(1, target + 1):
            assert h2.chain.store.get(r).signature == \
                sc.handlers[0].chain.store.get(r).signature
        # ...and the network keeps producing with node 2 back in
        sc.advance_round()
        sc.wait_all(target + 1)
    finally:
        sc.stop_all()


@pytest.mark.slow
def test_large_chaos_scenario_with_crash_windows():
    """Longer chain, more Byzantine peers, crash-restart windows layered on
    top of drops/delays/corruption — the kitchen-sink scenario stays
    deterministic and convergent."""
    chain = TrueChain(n=48)
    for seed in (7, 8, 9):
        sc = ChaosScenario(
            seed=seed, n_nodes=7, n_byzantine=3, rounds=48, chain=chain,
            byzantine_plan=dict(drop=0.3, delay=0.25, corrupt=0.4,
                                truncate=0.2, crash_at=1_050.0,
                                restart_at=1_500.0))
        r1 = sc.run()
        assert r1.converged, f"seed {seed} failed to converge"
        r2 = ChaosScenario(
            seed=seed, n_nodes=7, n_byzantine=3, rounds=48, chain=chain,
            byzantine_plan=dict(drop=0.3, delay=0.25, corrupt=0.4,
                                truncate=0.2, crash_at=1_050.0,
                                restart_at=1_500.0)).run()
        assert r2.converged and r2.chain_digest == r1.chain_digest
