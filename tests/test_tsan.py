"""tpu-tsan runtime sanitizer: wrapper semantics + detection + the
off-switch guarantee.

The wrappers (analysis/tsan.py) are tested directly — they work whether
or not DRAND_TSAN is set; the env var only controls what the
common.make_* factories hand out.  The off-switch test runs in a
subprocess so this process's own imports can't contaminate the
"sanitizer never imported" assertion.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

pytestmark = pytest.mark.tsan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from drand_tpu.analysis import tsan  # noqa: E402
from drand_tpu.common import make_condition, make_lock, make_rlock  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_registry():
    tsan.reset()
    yield
    tsan.reset()


# -- the off switch -----------------------------------------------------------


def test_factories_are_pure_passthrough_when_off():
    """DRAND_TSAN unset => stock threading primitives and the sanitizer
    module is never imported.  This is the zero-overhead contract the
    serving plane relies on; run out of process so nothing we imported
    here can leak into the check."""
    env = {k: v for k, v in os.environ.items() if k != "DRAND_TSAN"}
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import sys, threading\n"
        "import drand_tpu.common as c\n"
        "assert type(c.make_lock()) is type(threading.Lock())\n"
        "assert type(c.make_rlock()) is type(threading.RLock())\n"
        "assert isinstance(c.make_condition(), threading.Condition)\n"
        "assert 'drand_tpu.analysis.tsan' not in sys.modules\n"
        "print('ok')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "ok"


def test_factories_hand_out_wrappers_when_on(monkeypatch):
    monkeypatch.setenv("DRAND_TSAN", "1")
    assert isinstance(make_lock(), tsan.TsanLock)
    assert isinstance(make_rlock(), tsan.TsanRLock)
    cv = make_condition()
    assert isinstance(cv, threading.Condition)
    assert isinstance(cv._lock, tsan.TsanRLock)


# -- wrapper semantics --------------------------------------------------------


def test_lock_protocol_roundtrip():
    lk = tsan.instrumented_lock("t.proto")
    assert not lk.locked()
    with lk:
        assert lk.locked()
        assert lk._is_owned()
    assert not lk.locked()
    assert lk.acquire(blocking=False)
    lk.release()


def test_rlock_is_reentrant_without_findings():
    rl = tsan.instrumented_rlock("t.rl")
    with rl:
        with rl:
            assert rl._is_owned()
    assert tsan.findings() == []


def test_condition_wait_releases_and_reacquires():
    cv = threading.Condition(tsan.instrumented_rlock("t.cv"))
    fired = []

    def waker():
        with cv:
            fired.append(1)
            cv.notify_all()

    with cv:
        t = threading.Timer(0.05, waker)
        t.start()
        assert cv.wait(timeout=5)  # deadlocks here if wait keeps the lock
    t.join()
    assert fired == [1]
    assert tsan.findings() == []


# -- detection ----------------------------------------------------------------


def test_lock_order_cycle_detected():
    a = tsan.instrumented_lock("t.A")
    b = tsan.instrumented_lock("t.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    kinds = [f["kind"] for f in tsan.findings()]
    assert "lock-order-cycle" in kinds
    cyc = next(f for f in tsan.findings() if f["kind"] == "lock-order-cycle")
    assert "t.A" in cyc["detail"] and "t.B" in cyc["detail"]


def test_consistent_order_is_clean():
    a = tsan.instrumented_lock("t.A2")
    b = tsan.instrumented_lock("t.B2")
    for _ in range(3):
        with a:
            with b:
                pass
    assert tsan.findings() == []
    assert tsan.report()["edges"] == 1


def test_nonreentrant_reentry_detected():
    lk = tsan.instrumented_lock("t.re")
    lk.acquire()
    # re-entry is a same-thread property; an untimed second acquire
    # would truly deadlock, so use a timed one — the sanitizer records
    # the finding before blocking, and blocking-with-timeout still
    # counts (it deadlocks in production where nobody passes timeouts)
    assert not lk.acquire(blocking=True, timeout=0.05)
    lk.release()
    reentries = [f for f in tsan.findings() if f["kind"] == "reentry"]
    assert reentries and "t.re" in reentries[0]["detail"]


def test_try_acquire_contributes_no_edges_or_findings():
    a = tsan.instrumented_lock("t.tryA")
    b = tsan.instrumented_lock("t.tryB")
    with a:
        assert b.acquire(blocking=False)
        b.release()
        assert not a.acquire(blocking=False)  # re-entry probe, not a bug
    with b:
        assert a.acquire(blocking=False)
        a.release()
    assert tsan.findings() == []
    assert tsan.report()["edges"] == 0


def test_long_hold_is_warning_not_finding(monkeypatch):
    monkeypatch.setenv("DRAND_TSAN_HOLD_MS", "10")
    lk = tsan.instrumented_lock("t.hold")
    with lk:
        time.sleep(0.05)
    assert tsan.findings() == []
    warns = [w for w in tsan.warnings() if w["kind"] == "long-hold"]
    assert warns and "t.hold" in warns[0]["detail"]


# -- operator surface ---------------------------------------------------------


def test_held_locks_by_thread_snapshot():
    lk = tsan.instrumented_lock("t.heldsnap")
    inner = tsan.instrumented_lock("t.heldsnap2")
    ready = threading.Event()
    done = threading.Event()

    def holder():
        with lk:
            with inner:
                ready.set()
                done.wait(timeout=10)

    t = threading.Thread(target=holder, name="tsan-holder", daemon=True)
    t.start()
    assert ready.wait(timeout=10)
    try:
        table = tsan.held_locks_by_thread()
        held = table.get("tsan-holder", [])
        # names carry a #seq uniquifier; order is acquisition order
        assert [n.split("#")[0] for n in held] == \
            ["t.heldsnap", "t.heldsnap2"]
        rendered = tsan.render_held_table()
        assert "tsan-holder" in rendered and "t.heldsnap" in rendered
    finally:
        done.set()
        t.join(timeout=10)
    assert "tsan-holder" not in tsan.held_locks_by_thread()


def test_render_report_mentions_findings():
    a = tsan.instrumented_lock("t.rA")
    b = tsan.instrumented_lock("t.rB")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    text = tsan.render_report()
    assert "FINDING" in text and "t.rA" in text
