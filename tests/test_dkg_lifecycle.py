"""Crash-safe DKG/reshare lifecycle (core/dkg_journal.py + the
beacon_process staging/recovery paths): the tier-1 recovery matrix.

Everything here is CPU-fast — FakeClock, tmpdir FileStores, the
in-process `_LocalDkgNet` from tests/chaos.py instead of gRPC.  The live
crash-during-rounds scenarios (fake-time beacon production across a
restart) live in tests/chaos.py and run via `tools/chaos_smoke.py
--reshare`.
"""

import json
import os
import queue
import threading
import time

import pytest

from drand_tpu.beacon.clock import FakeClock
from drand_tpu.core import dkg_journal as J
from drand_tpu.core.beacon_process import (DKG_DONE, DKG_FAILED,
                                           DKG_IN_PROGRESS)
from drand_tpu.core.dkg_journal import DKGJournal, recover
from drand_tpu.core.dkg_runner import run_dkg_bounded
from drand_tpu.crypto import tbls
from drand_tpu.crypto.schemes import scheme_from_name
from drand_tpu.key import DistPublic, Share, new_group, new_keypair
from drand_tpu.key.store import FileStore
from drand_tpu.log import Logger
from drand_tpu.protos import drand_pb2 as pb

from chaos import AutoClock, DkgLifecycleHarness

SCHEME = scheme_from_name("pedersen-bls-chained")


# ---------------------------------------------------------------------------
# fixtures: a synthetic old/new group pair sharing one collective key
# ---------------------------------------------------------------------------


def _mini_state(tmp_path, n=3, thr=2, transition_offset=120):
    """FileStore + (old group with share) + (reshare group + new share)
    — shares fabricated from one polynomial (the harness pattern), so no
    DKG is needed to exercise the journal/ledger machinery."""
    pairs = [new_keypair(f"127.0.0.1:{9200 + i}", SCHEME,
                         seed=b"lifecycle%d" % i) for i in range(n)]
    genesis = 1_700_000_000
    old = new_group([p.public for p in pairs], thr, genesis=genesis,
                    period=30, catchup_period=5, scheme=SCHEME)
    poly = tbls.PriPoly.random(thr, secret=424242)
    commits = [SCHEME.key_group.to_bytes(c)
               for c in poly.commit(SCHEME.key_group).commits]
    old.public_key = DistPublic(commits)
    old_share = Share(scheme=SCHEME, private=poly.eval(0), commits=commits)

    new = new_group([p.public for p in pairs], thr, genesis=genesis,
                    period=30, catchup_period=5, scheme=SCHEME)
    new.genesis_seed = old.get_genesis_seed()
    new.transition_time = genesis + transition_offset
    # a reshare keeps commits[0] (the collective key); higher coefficients
    # change — a distinct polynomial with the same constant term
    poly2 = tbls.PriPoly.random(thr, secret=424242)
    commits2 = [SCHEME.key_group.to_bytes(c)
                for c in poly2.commit(SCHEME.key_group).commits]
    new.public_key = DistPublic(commits2)
    new_share = Share(scheme=SCHEME, private=poly2.eval(0), commits=commits2)

    fs = FileStore(str(tmp_path), "default")
    fs.save_group(old)
    fs.save_share(old_share)
    return fs, old, old_share, new, new_share


def _journal(fs, now=1_700_000_000):
    return DKGJournal(fs, clock=FakeClock(start=now))


# ---------------------------------------------------------------------------
# journal + ledger round-trips
# ---------------------------------------------------------------------------


def test_session_record_roundtrip(tmp_path):
    fs, *_ = _mini_state(tmp_path)
    j = _journal(fs)
    j.begin("reshare", "leader")
    j.set_nonce(b"\xaa" * 32)
    j.phase(J.PHASE_DEAL)
    rec = DKGJournal(fs).load_session()       # fresh instance: from disk
    assert rec.kind == "reshare" and rec.role == "leader"
    assert rec.nonce == "aa" * 32
    assert rec.phase == J.PHASE_DEAL and rec.outcome == J.RUNNING
    j.finish(J.SUCCESS)
    assert DKGJournal(fs).load_session().outcome == J.SUCCESS


def test_journal_tolerates_torn_session_file(tmp_path):
    fs, *_ = _mini_state(tmp_path)
    j = _journal(fs)
    j.begin("dkg", "follower")
    with open(j.session_path, "w") as f:
        f.write('{"beacon_id": "defau')       # torn JSON
    assert j.load_session() is None           # discarded, not trusted


def test_stage_leaves_active_untouched_then_commit_promotes(tmp_path):
    fs, old, old_share, new, new_share = _mini_state(tmp_path)
    j = _journal(fs)
    pending = j.stage_transition(old, new, new_share)
    # the crash window's invariant: active files still the OLD epoch
    assert fs.load_group().hash() == old.hash()
    assert fs.load_share().private.value == old_share.private.value
    assert fs.load_group(staged=True).hash() == new.hash()
    assert pending.transition_time == new.transition_time
    assert j.load_pending() is not None
    # commit: staged -> active, ledger retired
    assert j.commit_pending() is True
    assert fs.load_group().hash() == new.hash()
    assert fs.load_share().private.value == new_share.private.value
    assert fs.load_group(staged=True) is None
    assert j.load_pending() is None
    assert j.commit_pending() is False        # idempotent replay


def test_recover_rearm_before_transition(tmp_path):
    fs, old, old_share, new, new_share = _mini_state(tmp_path)
    j = _journal(fs)
    j.stage_transition(old, new, new_share)
    clock = FakeClock(start=new.transition_time - 50)
    rec = recover(j, clock, Logger("t"))
    assert rec.action == "rearm"
    assert rec.group.hash() == new.hash()
    assert rec.share.private.value == new_share.private.value
    # nothing moved: old state still active, ledger still armed
    assert fs.load_group().hash() == old.hash()
    assert j.load_pending() is not None


def test_recover_member_rearms_even_past_transition(tmp_path):
    """A running member NEVER commits on wall-clock time alone: its chain
    head may still sit below the transition round (a stalled old-key
    segment needs OLD shares), so recovery re-arms and the handler's
    time+round dual gate commits.  Old share intact until then."""
    fs, old, old_share, new, new_share = _mini_state(tmp_path)
    j = _journal(fs)
    j.stage_transition(old, new, new_share)
    clock = FakeClock(start=new.transition_time + 1000)
    rec = recover(j, clock, Logger("t"))
    assert rec.action == "rearm"
    assert fs.load_group().hash() == old.hash()
    assert fs.load_share().private.value == old_share.private.value
    assert j.load_pending() is not None


def test_recover_newcomer_commits_past_transition(tmp_path):
    """A newcomer has no old share to protect: past the transition time
    the staged state is committed immediately (start with catchup)."""
    fs, old, old_share, new, new_share = _mini_state(tmp_path)
    fs.reset()                                # newcomer: no active state
    j = _journal(fs)
    j.stage_transition(old, new, new_share)
    clock = FakeClock(start=new.transition_time + 1)
    rec = recover(j, clock, Logger("t"))
    assert rec.action == "committed"
    assert fs.load_group().hash() == new.hash()
    assert fs.load_share().private.value == new_share.private.value
    assert j.load_pending() is None


def test_recover_discards_tampered_staged_share(tmp_path):
    fs, old, old_share, new, new_share = _mini_state(tmp_path)
    j = _journal(fs)
    j.stage_transition(old, new, new_share)
    # flip one byte of the staged share: the ledger digest must catch it
    with open(fs.staged_share_file, "r+b") as f:
        b = bytearray(f.read())
        b[len(b) // 2] ^= 0x01
        f.seek(0)
        f.write(bytes(b))
    rec = recover(j, FakeClock(start=new.transition_time - 50), Logger("t"))
    assert rec.action == "discarded"
    # old state intact, staged garbage + ledger gone
    assert fs.load_group().hash() == old.hash()
    assert fs.load_share().private.value == old_share.private.value
    assert j.load_pending() is None
    assert not os.path.exists(fs.staged_share_file)


def test_recover_discards_when_staged_group_missing(tmp_path):
    fs, old, old_share, new, new_share = _mini_state(tmp_path)
    j = _journal(fs)
    j.stage_transition(old, new, new_share)
    os.remove(fs.staged_group_file)
    rec = recover(j, FakeClock(start=new.transition_time - 50), Logger("t"))
    assert rec.action == "discarded"
    assert fs.load_group().hash() == old.hash()
    assert j.load_pending() is None


def test_recover_finishes_half_committed_swap(tmp_path):
    """Crash in the middle of commit itself (newcomer: share promoted,
    group still staged, ledger present) — the replayed commit must finish
    the promotion, not discard it as tampered."""
    fs, old, old_share, new, new_share = _mini_state(tmp_path)
    fs.reset()                                # newcomer: no active state
    j = _journal(fs)
    j.stage_transition(old, new, new_share)
    os.replace(fs.staged_share_file, fs.share_file)   # half-done commit
    rec = recover(j, FakeClock(start=new.transition_time + 1), Logger("t"))
    assert rec.action == "committed"
    assert fs.load_group().hash() == new.hash()
    assert fs.load_share().private.value == new_share.private.value
    assert j.load_pending() is None


def test_recover_member_half_committed_rearms_and_commit_replays(tmp_path):
    """A MEMBER crashed mid-commit (possible only after the handler's
    time+round gate passed): recovery re-arms with the staged pair and a
    replayed commit_pending finishes the promotion idempotently."""
    fs, old, old_share, new, new_share = _mini_state(tmp_path)
    j = _journal(fs)
    j.stage_transition(old, new, new_share)
    os.replace(fs.staged_share_file, fs.share_file)   # half-done commit
    rec = recover(j, FakeClock(start=new.transition_time + 1), Logger("t"))
    assert rec.action == "rearm"
    assert rec.group.hash() == new.hash()             # staged pair intact
    assert j.commit_pending() is True                 # the replay finishes
    assert fs.load_group().hash() == new.hash()
    assert j.load_pending() is None


def test_leaver_commit_promotes_group_and_drops_share(tmp_path):
    fs, old, old_share, new, _ = _mini_state(tmp_path)
    j = _journal(fs)
    j.stage_transition(old, new, None)        # not in the new group
    assert j.load_pending().has_share is False
    assert j.commit_pending() is True
    assert fs.load_group().hash() == new.hash()
    assert fs.load_share() is None            # old share retired with exit


def test_recover_marks_crashed_session_aborted(tmp_path):
    fs, *_ = _mini_state(tmp_path)
    j = _journal(fs)
    j.begin("dkg", "follower", nonce=b"\xcd" * 32)
    j.phase(J.PHASE_DEAL)                     # ...and the process dies here
    rec = recover(j, FakeClock(start=1), Logger("t"))
    assert rec.action == "none"
    assert rec.aborted_session is not None
    assert rec.aborted_session.phase == J.PHASE_DEAL
    assert DKGJournal(fs).load_session().outcome == J.ABORTED


# ---------------------------------------------------------------------------
# atomic persistence (key/store.py via fs.write_atomic)
# ---------------------------------------------------------------------------


def test_write_atomic_no_residue_and_secure_mode(tmp_path):
    from drand_tpu import fs as F
    p = str(tmp_path / "x.toml")
    F.write_atomic(p, b"one")
    F.write_atomic(p, b"two", secure=True)
    assert open(p, "rb").read() == b"two"
    assert os.stat(p).st_mode & 0o077 == 0    # owner-only
    # no temp siblings left behind
    assert [f for f in os.listdir(tmp_path) if f != "x.toml"] == []


def test_share_file_is_owner_only(tmp_path):
    fs, old, old_share, new, new_share = _mini_state(tmp_path)
    assert os.stat(fs.share_file).st_mode & 0o077 == 0
    fs.save_share(new_share, staged=True)
    assert os.stat(fs.staged_share_file).st_mode & 0o077 == 0


# ---------------------------------------------------------------------------
# failure hygiene at the BeaconProcess level (no network, no beacons)
# ---------------------------------------------------------------------------


def test_leader_setup_timeout_sets_dkg_failed_then_retry_succeeds(tmp_path):
    h = DkgLifecycleHarness(str(tmp_path), n=3)
    try:
        from drand_tpu.crypto.schemes import get_scheme_by_id_with_default
        with pytest.raises(TimeoutError):
            # nobody signals: wait_participants expires (real seconds)
            h.bps[0].init_dkg_leader(
                n_nodes=3, threshold=2, period=30, catchup_period=5,
                secret=b"s", setup_timeout=0.2,
                scheme=get_scheme_by_id_with_default(""))
        assert h.bps[0].dkg_status == DKG_FAILED
        assert h.bps[0].journal.load_session().outcome == J.FAILED
        # the beacon is immediately serveable for a fresh session
        group = h.run_dkg(threshold=2, start_beacons=False)
        assert group is not None
        assert all(h.bps[i].dkg_status == DKG_DONE for i in range(3))
    finally:
        h.stop_all()


def test_join_unreachable_leader_sets_dkg_failed(tmp_path):
    from drand_tpu.net import Peer
    h = DkgLifecycleHarness(str(tmp_path), n=2,
                            clock=AutoClock(start=1_700_000_000.0))
    try:
        h.net.kill(h.addrs[0])
        with pytest.raises(Exception):
            h.bps[1].join_dkg(leader=Peer(h.addrs[0]), secret=b"s",
                              setup_timeout=5.0)
        assert h.bps[1].dkg_status == DKG_FAILED
        assert h.bps[1].fs.load_group(staged=True) is None
    finally:
        h.stop_all()


def test_partial_push_arming_unwinds_to_dkg_failed(tmp_path):
    """ISSUE 12 satellite: the leader's group push fails against a SUBSET
    of followers.  The leader fails immediately; the follower that WAS
    armed must unwind via its phase deadlines to DKG_FAILED — never a
    wedged WAITING/IN_PROGRESS — and a fresh session on the same beacons
    must succeed."""
    from drand_tpu.crypto.schemes import get_scheme_by_id_with_default
    from drand_tpu.net import Peer

    h = DkgLifecycleHarness(str(tmp_path), n=3)
    try:
        h.net.fail_push_to.add(h.addrs[2])    # bp2 refuses the group push
        errors = []

        def lead():
            try:
                h.bps[0].init_dkg_leader(
                    n_nodes=3, threshold=2, period=30, catchup_period=5,
                    secret=b"s", setup_timeout=20.0,
                    scheme=get_scheme_by_id_with_default(""))
            except Exception as e:
                errors.append(e)

        def follow(i, timeout):
            try:
                h.bps[i].join_dkg(leader=Peer(h.addrs[0]), secret=b"s",
                                  setup_timeout=timeout)
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=lead, daemon=True),
                   threading.Thread(target=follow, args=(1, 20.0),
                                    daemon=True),
                   threading.Thread(target=follow, args=(2, 2.0),
                                    daemon=True)]
        threads[0].start()
        h._await_setup(h.bps[0])
        for t in threads[1:]:
            t.start()
        # bp1 got the group and armed a session that will never run:
        # advance fake time until its phase deadlines unwind it
        deadline = time.monotonic() + 60
        while any(t.is_alive() for t in threads):
            h.clock.advance(10)
            time.sleep(0.05)
            assert time.monotonic() < deadline, "sessions never unwound"
        assert len(errors) == 3               # all three attempts failed
        assert h.bps[0].dkg_status == DKG_FAILED
        assert h.bps[1].dkg_status == DKG_FAILED, \
            "armed follower wedged instead of unwinding to DKG_FAILED"
        assert h.bps[1].dkg_status != DKG_IN_PROGRESS
        assert h.bps[2].dkg_status == DKG_FAILED
        # retry with the push fixed: same processes, fresh session
        h.net.fail_push_to.clear()
        group = h.run_dkg(threshold=2, secret=b"retry",
                          start_beacons=False)
        assert group is not None
        assert all(h.bps[i].dkg_status == DKG_DONE for i in range(3))
    finally:
        h.stop_all()


def test_stale_epoch_bundle_rejected_by_nonce(tmp_path):
    h = DkgLifecycleHarness(str(tmp_path), n=2)
    try:
        bp = h.bps[0]
        dead = b"\xee" * 32
        bp._fail_session("dkg", dead)
        stale = pb.DKGPacket(dkg=pb.DKGBundle(
            deal=pb.DealBundle(dealer_index=1, session_id=dead)))
        with pytest.raises(ValueError, match="stale"):
            bp.broadcast_dkg(stale)
        # an unrelated epoch's early bundle still parks for the next board
        fresh = pb.DKGPacket(dkg=pb.DKGBundle(
            deal=pb.DealBundle(dealer_index=1, session_id=b"\x01" * 32)))
        bp.broadcast_dkg(fresh)
        assert len(bp._pending_dkg) == 1
    finally:
        h.stop_all()


def test_retry_with_identical_group_hash_unblacklists_nonce(tmp_path):
    """A reshare retry can legitimately reuse the failed attempt's group
    hash (same membership/threshold/transition round): the moment a local
    session re-adopts the nonce it leaves the blacklist, or the node
    would reject every bundle of its own retry."""
    h = DkgLifecycleHarness(str(tmp_path), n=2)
    try:
        bp = h.bps[0]
        dead = b"\xdd" * 32
        bp._fail_session("dkg", dead)
        stale = pb.DKGPacket(dkg=pb.DKGBundle(
            deal=pb.DealBundle(dealer_index=1, session_id=dead)))
        with pytest.raises(ValueError):
            bp.broadcast_dkg(stale)
        with bp._lock:
            bp._failed_nonces.discard(dead)   # what _run_dkg_session does
        bp.broadcast_dkg(stale)               # parks, no longer rejected
        assert len(bp._pending_dkg) == 1
    finally:
        h.stop_all()


def test_public_files_stay_world_readable(tmp_path):
    """write_atomic must not silently tighten PUBLIC artifacts to 0600:
    the group TOML and the public identity are read by sidecar tooling
    (only secure=True files are owner-only)."""
    import stat
    fs, old, *_ = _mini_state(tmp_path)
    pair = new_keypair("127.0.0.1:9999", SCHEME, seed=b"perm")
    fs.save_keypair(pair)
    um = os.umask(0)
    os.umask(um)
    want = 0o666 & ~um
    assert os.stat(fs.group_file).st_mode & 0o777 == want
    assert os.stat(fs.public_key_file).st_mode & 0o777 == want
    assert stat.S_IMODE(os.stat(fs.private_key_file).st_mode) == 0o600


def test_failed_session_cleans_staged_output_only_for_its_epoch(tmp_path):
    """A pending ledger staged by an EARLIER successful reshare must
    survive a later unrelated session's failure."""
    fs, old, old_share, new, new_share = _mini_state(tmp_path / "state")
    h = DkgLifecycleHarness(str(tmp_path / "net"), n=2)
    try:
        bp = h.bps[0]
        bp.journal.stage_transition(old, new, new_share)
        bp._fail_session("dkg", b"\x99" * 32)     # some other epoch
        assert bp.journal.load_pending() is not None
        # ...but the failing epoch's own staged output IS discarded
        bp._fail_session("reshare", bytes.fromhex(
            bp.journal.load_pending().new_group_hash))
        assert bp.journal.load_pending() is None
    finally:
        h.stop_all()


# ---------------------------------------------------------------------------
# the session deadline (run_dkg_bounded)
# ---------------------------------------------------------------------------


class _WedgedBoard:
    """A board whose queues never fill — the wedged-collect hang."""

    def __init__(self):
        self.deals = queue.Queue()
        self.responses = queue.Queue()
        self.justifications = queue.Queue()
        self._stop = threading.Event()

    def to_network(self, bundle):
        pass

    def collect(self, q, want, deadline, clock):
        # deliberately IGNORES the phase deadline — the wedged-collect
        # bug class the session deadline exists to contain
        out = []
        while len(out) < want and not self._stop.is_set():
            try:
                out.append(q.get(timeout=0.05))
            except queue.Empty:
                continue
        return out

    def stop(self):
        self._stop.set()


class _IdleGen:
    dealers = [1, 2]
    holders = [1, 2]

    def generate_deals(self):
        return None

    def process_deal_bundles(self, deals):
        raise AssertionError("phase must never complete on a wedged board")


def test_session_deadline_frees_wedged_collect_real_cap(tmp_path):
    """A frozen injected clock must not wedge the control RPC: the
    real-seconds cap abandons the session."""
    board = _WedgedBoard()
    clock = FakeClock(start=1000.0)           # frozen: fake deadline never
    t0 = time.monotonic()
    try:
        with pytest.raises(TimeoutError, match="budget"):
            run_dkg_bounded(_IdleGen(), board, clock, phase_timeout=100,
                            log=Logger("t"), real_cap=1.0)
        assert time.monotonic() - t0 < 30
    finally:
        board.stop()


class _QuietGen(_IdleGen):
    """Tolerates empty phases, so the unwinding worker would reach every
    later on_phase call if it were not muted."""

    def process_deal_bundles(self, deals):
        return None

    def process_response_bundles(self, resps):
        return None, None

    def process_justification_bundles(self, justs):
        raise RuntimeError("no justifications")


def test_abandoned_session_worker_goes_mute(tmp_path):
    """After the session deadline trips, the unwinding worker must not
    keep firing on_phase — late phase writes would scribble over the
    journal/gauge of the failed (or a retried) session."""
    board = _WedgedBoard()
    clock = FakeClock(start=1000.0)
    phases = []
    with pytest.raises(TimeoutError):
        run_dkg_bounded(_QuietGen(), board, clock, phase_timeout=100,
                        log=Logger("t"), real_cap=0.5,
                        on_phase=phases.append)
    seen_at_timeout = list(phases)
    board.stop()   # the abandoned collect unwinds through later phases
    time.sleep(0.5)
    assert phases == seen_at_timeout, \
        f"abandoned worker kept journaling: {phases[len(seen_at_timeout):]}"


def test_session_deadline_trips_on_clock(tmp_path):
    """The clock-based budget trips as fake time advances (the production
    path under a real clock)."""
    board = _WedgedBoard()
    clock = FakeClock(start=1000.0)

    def advance():
        for _ in range(40):
            clock.advance(5.0)
            time.sleep(0.02)

    t = threading.Thread(target=advance, daemon=True)
    t.start()
    try:
        with pytest.raises(TimeoutError):
            run_dkg_bounded(_IdleGen(), board, clock, phase_timeout=10,
                            log=Logger("t"), session_budget=30.0,
                            real_cap=60.0)
    finally:
        board.stop()
        t.join(timeout=5)


# ---------------------------------------------------------------------------
# restart recovery through BeaconProcess.load (no live rounds)
# ---------------------------------------------------------------------------


def _stage_on(bp, transition_offset=120):
    """Give bp on-disk old state + a staged reshare, as a successful
    session would have left them."""
    fs, old, old_share, new, new_share = _mini_state(
        bp.cfg.folder + "-src", transition_offset=transition_offset)
    bp.fs.save_group(old)
    bp.fs.save_share(old_share)
    bp.journal.stage_transition(old, new, new_share)
    return old, new


def test_load_rearms_running_member_before_transition(tmp_path):
    h = DkgLifecycleHarness(str(tmp_path), n=2)
    try:
        bp = h.bps[0]
        old, new = _stage_on(bp)
        h.clock.set_time(new.transition_time - 60)
        assert bp.load() is True
        # old epoch active, swap armed for start_beacon
        assert bp.group.hash() == old.hash()
        assert bp._armed_transition is not None
        assert bp._armed_transition[0].hash() == new.hash()
        assert bp.reshare_status == DKG_DONE
        assert bp.journal.load_pending() is not None
    finally:
        h.stop_all()


def test_load_member_rearms_past_transition_keeps_old_share(tmp_path):
    """A member restarting AFTER the transition time still re-arms: the
    old share must survive until the chain head provably crosses the
    transition round (catch-up sync + the handler gate handle the rest)."""
    h = DkgLifecycleHarness(str(tmp_path), n=2)
    try:
        bp = h.bps[0]
        old, new = _stage_on(bp, transition_offset=-10)   # already past
        assert bp.load() is True
        assert bp.group.hash() == old.hash()              # old epoch serves
        assert bp._armed_transition is not None
        assert bp.journal.load_pending() is not None
    finally:
        h.stop_all()


def test_load_newcomer_commits_immediately_past_transition(tmp_path):
    h = DkgLifecycleHarness(str(tmp_path), n=2)
    try:
        bp = h.bps[0]
        fs_src, old, osh, new, nsh = _mini_state(
            bp.cfg.folder + "-src", transition_offset=-10)
        bp.journal.stage_transition(old, new, nsh)        # no active state
        assert bp.load() is True
        assert bp.group.hash() == new.hash()
        assert bp._armed_transition is None
        assert bp.journal.load_pending() is None
        assert bp.fs.load_group().hash() == new.hash()
    finally:
        h.stop_all()


def test_load_discards_tampered_ledger_keeps_old_state(tmp_path):
    h = DkgLifecycleHarness(str(tmp_path), n=2)
    try:
        bp = h.bps[0]
        old, new = _stage_on(bp)
        os.remove(bp.fs.staged_share_file)                # tamper
        h.clock.set_time(new.transition_time - 60)
        assert bp.load() is True
        assert bp.group.hash() == old.hash()
        assert bp._armed_transition is None
        assert bp.journal.load_pending() is None
    finally:
        h.stop_all()


# ---------------------------------------------------------------------------
# observability: metrics + the /health dkg block
# ---------------------------------------------------------------------------


def test_dkg_metrics_move_on_failure(tmp_path):
    from drand_tpu.metrics import dkg_sessions
    h = DkgLifecycleHarness(str(tmp_path), n=2)
    try:
        before = dkg_sessions.labels("default", "dkg",
                                     J.FAILED)._value.get()
        h.bps[0]._fail_session("dkg", b"\x10" * 32)
        assert dkg_sessions.labels("default", "dkg",
                                   J.FAILED)._value.get() == before + 1
    finally:
        h.stop_all()


def test_health_carries_dkg_block(tmp_path):
    from drand_tpu.http_server import RestServer

    h = DkgLifecycleHarness(str(tmp_path), n=2)
    server = None
    try:
        bp = h.bps[0]
        old, new = _stage_on(bp)
        h.clock.set_time(new.transition_time - 60)
        bp.load()

        class _ShimDaemon:
            processes = {"default": bp}
            chain_hashes = {}
            log = Logger("t")

        server = RestServer(_ShimDaemon(), "127.0.0.1:0", clock=h.clock)
        code, body, _ = server._route("/health")
        payload = json.loads(body)
        assert "dkg" in payload
        assert payload["dkg"]["reshare"] == "done"
        assert payload["dkg"]["transition_pending"] is True
        assert payload["dkg"]["transition_time"] == new.transition_time
    finally:
        if server is not None:
            server.httpd.server_close()
        h.stop_all()
