"""Process-fleet supervisor: N real daemons, seeded faults, invariants.

Everything in-process chaos cannot reach lives here: real `DrandDaemon`
processes (subprocess, own folder + sqlite store each), a live-gRPC
coordinated DKG, and a seeded fault schedule — SIGKILL/SIGSTOP/SIGTERM,
rolling restarts, and link faults through the per-link userspace TCP
proxy (drand_tpu/net/chaosproxy.py).  No root, no iptables: each daemon
is pointed at its own proxy addresses via the `DRAND_DIAL_MAP` file
indirection in net/client.py, and the proxies live in THIS process, so
fault injection is a method call.

The module is import-style shared between the pytest smoke soak
(tests/test_fleet.py), the operator CLI (tools/fleet.py), and
`tools/chaos_smoke.py --fleet`.

Deadline discipline (enforced by tpu-vet's `deadline` checker, which
scopes this file BY NAME despite tests/ being otherwise exempt): every
subprocess wait, ready-file poll, and RPC loop carries a hard deadline —
a wedged fleet run must die in minutes, not hang CI.

Invariants checked during/after a soak (`FleetInvariants`):

  * no fork     — byte-identical beacon signatures across every node at
                  every verified round;
  * liveness    — rounds advance within the budget while >= threshold
                  nodes are connected;
  * recovery    — a killed/partitioned node catches up after heal;
  * teardown    — SIGTERM exits 0 (graceful drain, no leaked service
                  threads; cli.cmd_start returns 3 on a leak).
"""

import hashlib
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:                           # tools/ entry points
    sys.path.insert(0, _REPO)

from drand_tpu.net import ControlClient, Peer, ProtocolClient, ProxyMesh
from drand_tpu.net import convert
from drand_tpu.protos import drand_pb2 as pb

SECRET = b"fleet-secret"

# how long a spawned daemon gets to publish its ready file; generous for
# a loaded CI box (cold JAX import dominates)
READY_TIMEOUT = 90.0
REAP_TIMEOUT = 30.0


class FleetError(AssertionError):
    """An invariant or supervisor-level failure; carries enough context
    to diagnose without re-running."""


# -- one daemon process -------------------------------------------------------

class FleetNode:
    """One real daemon process plus its folder, ready info, and signal
    surface.  Restarts re-pin the original private/control ports so the
    roster (and the proxy mesh upstreams) stay valid across the restart."""

    def __init__(self, name: str, folder: str, env: dict, period: int,
                 dkg_timeout: int, grace: float, identity_dir=None,
                 log=None):
        self.name = name
        self.folder = folder
        self.env = env
        self.period = period
        self.dkg_timeout = dkg_timeout
        self.grace = grace
        self.identity_dir = identity_dir
        self.proc = None
        self.ready = {}             # pid/private/control/metrics/public
        self.starts = 0
        self._log = log or (lambda *_: None)
        os.makedirs(folder, exist_ok=True)

    @property
    def ready_path(self) -> str:
        return os.path.join(self.folder, "ready.json")

    @property
    def private(self) -> str:
        return self.ready["private"]

    @property
    def control(self) -> int:
        return self.ready["control"]

    def spawn(self, private_listen: str = "127.0.0.1:0",
              control: int = 0) -> None:
        if self.proc is not None and self.proc.poll() is None:
            raise FleetError(f"{self.name}: already running")
        try:
            os.unlink(self.ready_path)
        except OSError:
            pass
        cmd = [sys.executable, "-m", "drand_tpu.cli", "start",
               "--folder", self.folder,
               "--private-listen", private_listen,
               "--control", str(control),
               "--metrics", "0",
               "--db", "sqlite",
               "--no-tpu",
               "--dkg-timeout", str(self.dkg_timeout),
               "--ready-file", self.ready_path,
               "--grace", str(self.grace)]
        if self.identity_dir:
            cmd += ["--identity-dir", self.identity_dir]
        logf = open(os.path.join(self.folder, f"log.{self.starts}.txt"),
                    "ab")
        self.proc = subprocess.Popen(cmd, env=self.env, stdout=logf,
                                     stderr=subprocess.STDOUT, cwd=_REPO)
        logf.close()                # the child holds its own fd now
        self.starts += 1
        self._log(f"{self.name}: spawned pid={self.proc.pid} "
                  f"listen={private_listen} control={control}")

    def wait_ready(self, timeout: float = READY_TIMEOUT) -> dict:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise FleetError(
                    f"{self.name}: daemon died rc={self.proc.returncode} "
                    f"before ready (see {self.folder}/log.*.txt)")
            try:
                with open(self.ready_path) as f:
                    self.ready = json.load(f)
                return self.ready
            except (OSError, ValueError):
                time.sleep(0.1)
        raise FleetError(f"{self.name}: not ready within {timeout}s")

    def restart(self, timeout: float = READY_TIMEOUT) -> dict:
        """Respawn with the ORIGINAL private/control ports re-pinned, so
        the group roster (peer addresses inside the signed group file)
        and the proxy upstreams remain correct."""
        if self.proc is not None and self.proc.poll() is None:
            raise FleetError(f"{self.name}: still running; kill first")
        self.spawn(private_listen=self.private, control=self.control)
        return self.wait_ready(timeout)

    # -- signals ------------------------------------------------------------

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def sigterm(self) -> None:
        self._signal(signal.SIGTERM)

    def sigstop(self) -> None:
        self._signal(signal.SIGSTOP)

    def sigcont(self) -> None:
        self._signal(signal.SIGCONT)

    def _signal(self, sig) -> None:
        self._log(f"{self.name}: signal {sig!r}")
        self.proc.send_signal(sig)

    def reap(self, timeout: float = REAP_TIMEOUT) -> int:
        """Wait (bounded) for exit; SIGKILL + reap on overrun so the
        supervisor never leaks a child, and return the exit code."""
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self._log(f"{self.name}: reap overran {timeout}s; SIGKILL")
            self.proc.kill()
            return self.proc.wait(timeout=10)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


# -- the seeded fault schedule ------------------------------------------------

class FaultPlan:
    """Deterministic fault schedule: same (seed, n, rounds) => same
    events, byte for byte — `digest()` is the identity a CI log prints
    so a failure reproduces locally with one seed value.

    Events are (at_round, kind, params) with kinds:

      kill_restart      SIGKILL one member, restart it two rounds later
      sigterm_restart   graceful stop + restart (rolling restart)
      freeze            SIGSTOP, SIGCONT after `hold` rounds
      partition_heal    drop links across a seeded A|B cut, heal after
                        `hold` rounds (minority side always < threshold
                        complement, so the majority keeps the chain live)
      delay_link        add per-chunk latency on one directed link
      reset_link        hard-RST the streams of one directed link
    """

    KINDS = ("kill_restart", "sigterm_restart", "freeze",
             "partition_heal", "delay_link", "reset_link")

    def __init__(self, seed: int, n: int, rounds: int,
                 kinds=None):
        self.seed, self.n, self.rounds = seed, n, rounds
        rng = random.Random(seed)
        kinds = tuple(kinds or self.KINDS)
        names = [f"n{i}" for i in range(n)]
        self.events = []
        r = 2                       # let the chain establish first
        while r < rounds - 1:
            kind = kinds[rng.randrange(len(kinds))]
            if kind == "kill_restart":
                self.events.append((r, kind, {"node": rng.choice(names),
                                              "restart_after": 2}))
                r += 3
            elif kind == "sigterm_restart":
                self.events.append((r, kind, {"node": rng.choice(names)}))
                r += 3
            elif kind == "freeze":
                self.events.append((r, kind, {"node": rng.choice(names),
                                              "hold": 1}))
                r += 2
            elif kind == "partition_heal":
                minority = rng.sample(names, max(1, (n - 1) // 2))
                self.events.append((r, kind, {"minority": sorted(minority),
                                              "hold": 2}))
                r += 4
            elif kind == "delay_link":
                src, dst = rng.sample(names, 2)
                self.events.append((r, kind, {"src": src, "dst": dst,
                                              "delay": 0.2, "hold": 1}))
                r += 2
            else:                   # reset_link
                src, dst = rng.sample(names, 2)
                self.events.append((r, kind, {"src": src, "dst": dst}))
                r += 1

    def digest(self) -> str:
        ident = repr((self.seed, self.n, self.rounds, self.events))
        return hashlib.sha256(ident.encode()).hexdigest()[:16]


# -- the fleet ----------------------------------------------------------------

class Fleet:
    """Supervisor for N daemon processes wired through a ProxyMesh.

    Lifecycle: start() -> run_dkg() -> (faults + wait_round/soak) ->
    stop_all().  Context-manager use guarantees teardown even on a
    failed invariant: every child is reaped and every proxy stopped."""

    def __init__(self, n: int, base_dir: str, period: int = 3,
                 threshold=None, handel_min_group: int = 2,
                 dkg_timeout: int = 5, grace: float = 5.0, seed: int = 0,
                 mtls: bool = False, log=print):
        self.n = n
        self.period = period
        self.threshold = threshold or (n // 2 + 1)
        self.grace = grace
        self.seed = seed
        self.mtls = mtls
        self.log = log or (lambda *_: None)
        self.mesh = ProxyMesh()
        # mTLS fleet (ISSUE 19): one private CA under base_dir/identity,
        # a cert dir per node (SANs 127.0.0.1 + localhost, so roster and
        # proxy dials both verify) plus a supervisor cert — the server
        # side REQUIRES client auth, so the observation clients below
        # must present one too
        self.identity_dirs = {}
        self.supervisor_identity = None
        if mtls:
            from drand_tpu.net import provision_fleet
            self.identity_dirs = provision_fleet(
                os.path.join(base_dir, "identity"),
                {f"n{i}": ["127.0.0.1"] for i in range(n)}
                | {"supervisor": ["127.0.0.1"]})
            self.supervisor_identity = self.identity_dirs["supervisor"]
        self.client = ProtocolClient(
            identity=self._supervisor_plane())    # direct, unproxied
        self.nodes = {}
        for i in range(n):
            name = f"n{i}"
            folder = os.path.join(base_dir, name)
            env = dict(os.environ)
            env.update({
                "JAX_PLATFORMS": "cpu",
                "DRAND_HANDEL_MIN_GROUP": str(handel_min_group),
                "DRAND_DIAL_MAP": os.path.join(folder, "dialmap.json"),
            })
            # the supervisor may itself run under a dial map (nested
            # harnesses); never inherit it into the children
            env.pop("DRAND_READY_FILE", None)
            self.nodes[name] = FleetNode(
                name, folder, env, period, dkg_timeout, grace,
                identity_dir=self.identity_dirs.get(name),
                log=self.log)

    def _supervisor_plane(self):
        if self.supervisor_identity is None:
            return None
        from drand_tpu.net import IdentityPlane
        return IdentityPlane(self.supervisor_identity)

    def _control(self, name: str) -> ControlClient:
        return ControlClient(self.nodes[name].control,
                             identity_dir=self.supervisor_identity)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.teardown()
        return False

    # -- lifecycle -----------------------------------------------------------

    def start(self, ready_timeout: float = READY_TIMEOUT) -> None:
        """Spawn every daemon, collect the roster from the ready files,
        build the full proxy mesh, and hand each daemon its dial map."""
        for node in self.nodes.values():
            node.spawn()
        for node in self.nodes.values():
            node.wait_ready(ready_timeout)
        self.mesh.build({nm: nd.private for nm, nd in self.nodes.items()})
        for name in self.nodes:
            self._write_dial_map(name)
        self.log(f"fleet up: {self.n} daemons, "
                 f"{sum(1 for _ in self.mesh.links())} proxied links")

    def _write_dial_map(self, name: str) -> None:
        node = self.nodes[name]
        path = node.env["DRAND_DIAL_MAP"]
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.mesh.dial_map_for(name), f)
        os.replace(tmp, path)

    def run_dkg(self, timeout: float = 120.0, beacon_id: str = "default"):
        """Coordinated DKG over live gRPC: node n0 leads, everyone else
        retry-joins until the leader's setup phase accepts (mirrors
        tests/test_daemon_e2e, but across process boundaries)."""
        names = sorted(self.nodes)
        leader = self.nodes[names[0]]
        results, errors = {}, []

        def drive(name, req):
            cc = self._control(name)
            join_deadline = time.monotonic() + timeout
            while True:
                try:
                    results[name] = cc.stub.init_dkg(req, timeout=timeout)
                    return
                except Exception as e:
                    if name == names[0] \
                            or time.monotonic() >= join_deadline:
                        errors.append((name, e))
                        return
                    time.sleep(0.3)

        lead_req = pb.InitDKGPacket(
            info=pb.SetupInfo(leader=True, nodes=self.n,
                              threshold=self.threshold,
                              timeout_seconds=int(timeout), secret=SECRET),
            beacon_period_seconds=self.period,
            metadata=convert.metadata(beacon_id))
        join_req = pb.InitDKGPacket(
            info=pb.SetupInfo(leader=False, leader_address=leader.private,
                              timeout_seconds=int(timeout), secret=SECRET),
            metadata=convert.metadata(beacon_id))
        threads = [threading.Thread(
            target=drive, name=f"dkg-fleet-{nm}",
            args=(nm, lead_req if nm == names[0] else join_req))
            for nm in names]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout + 30)
        if errors:
            raise FleetError(f"DKG failed: {errors}")
        groups = {nm: convert.proto_to_group(r)
                  for nm, r in results.items()}
        hashes = {g.hash() for g in groups.values()}
        if len(hashes) != 1:
            raise FleetError(f"group divergence across nodes: {hashes}")
        keys = {g.public_key.key() for g in groups.values()}
        if len(keys) != 1:
            raise FleetError("collective key fork (QUAL divergence)")
        self.log(f"DKG complete: group hash "
                 f"{next(iter(hashes)).hex()[:16]}")
        return next(iter(groups.values()))

    # -- observation (all via DIRECT, unproxied connections) -----------------

    def head(self, name: str):
        """Latest beacon of one node, or None while it is unreachable
        (the client's own resilience timeout bounds the call)."""
        node = self.nodes[name]
        try:
            return self.client.public_rand(Peer(node.private), 0, "default")
        except Exception:
            return None

    def beacon(self, name: str, round_: int):
        node = self.nodes[name]
        try:
            return self.client.public_rand(
                Peer(node.private), round_, "default")
        except Exception:
            return None

    def wait_round(self, round_: int, timeout: float,
                   nodes=None) -> None:
        """Block until every named (default: every live) node serves
        `round_`; the liveness invariant is this call not overrunning."""
        names = list(nodes or [nm for nm, nd in self.nodes.items()
                               if nd.alive()])
        deadline = time.monotonic() + timeout
        pending = set(names)
        while pending and time.monotonic() < deadline:
            for nm in sorted(pending):
                r = self.head(nm)
                if r is not None and r.round >= round_:
                    pending.discard(nm)
            if pending:
                time.sleep(0.3)
        if pending:
            heads = {nm: getattr(self.head(nm), "round", None)
                     for nm in names}
            raise FleetError(
                f"liveness: round {round_} not reached on {sorted(pending)} "
                f"within {timeout}s (heads={heads})")

    def liveness_budget(self, rounds: int = 1) -> float:
        """How long `rounds` more rounds may take: the period per round
        plus a catch-up/aggregation allowance — generous because CI boxes
        run CPU pairings (~0.6 s each) under load."""
        return rounds * self.period + 12 * self.period

    # -- seeded fault execution ----------------------------------------------

    def execute(self, plan: FaultPlan) -> None:
        """Run the plan: advance round by round, injecting each event at
        its round boundary, and verify liveness of the untouched majority
        throughout.  Deferred un-faults (restarts, heals) fire at their
        scheduled round."""
        self.log(f"executing plan seed={plan.seed} "
                 f"digest={plan.digest()} events={len(plan.events)}")
        pending = []                # (at_round, fn, label)
        max_round = plan.rounds
        schedule = list(plan.events)
        for r in range(1, max_round + 1):
            for at, fn, label in [p for p in pending if p[0] <= r]:
                self.log(f"round {r}: deferred {label}")
                fn()
            pending = [p for p in pending if p[0] > r]
            while schedule and schedule[0][0] <= r:
                _, kind, params = schedule.pop(0)
                self.log(f"round {r}: inject {kind} {params}")
                pending.extend(self._inject(r, kind, params))
            healthy = self._healthy_names()
            if len(healthy) >= self.threshold:
                self.wait_round(r, self.liveness_budget(), nodes=healthy)
        # flush any still-deferred heals/restarts, then let everyone
        # converge on the final round
        for _, fn, label in pending:
            self.log(f"flush deferred {label}")
            fn()
        self.wait_round(max_round, self.liveness_budget(4),
                        nodes=list(self.nodes))

    def _healthy_names(self):
        return [nm for nm, nd in self.nodes.items()
                if nd.alive() and nm not in self._faulted]

    _faulted = frozenset()          # names currently killed/frozen/cut

    def _inject(self, r: int, kind: str, params: dict):
        """Apply one event; returns deferred (at_round, fn, label)
        un-fault actions."""
        deferred = []
        faulted = set(self._faulted)
        if kind == "kill_restart":
            nm = params["node"]
            self.nodes[nm].kill()
            self.nodes[nm].reap()
            faulted.add(nm)

            def restart(nm=nm):
                self.nodes[nm].restart()
                self._write_dial_map(nm)
                self._faulted = frozenset(self._faulted - {nm})
            deferred.append((r + params.get("restart_after", 2), restart,
                             f"restart {nm}"))
        elif kind == "sigterm_restart":
            nm = params["node"]
            self.nodes[nm].sigterm()
            rc = self.nodes[nm].reap()
            if rc != 0:
                raise FleetError(
                    f"{nm}: SIGTERM exit rc={rc} (want 0: graceful drain "
                    "failed or service threads leaked)")
            self.nodes[nm].restart()
            self._write_dial_map(nm)
        elif kind == "freeze":
            nm = params["node"]
            self.nodes[nm].sigstop()
            faulted.add(nm)

            def thaw(nm=nm):
                self.nodes[nm].sigcont()
                self._faulted = frozenset(self._faulted - {nm})
            deferred.append((r + params.get("hold", 1), thaw,
                             f"thaw {nm}"))
        elif kind == "partition_heal":
            minority = list(params["minority"])
            majority = [nm for nm in self.nodes if nm not in minority]
            self.mesh.partition(minority, majority)
            faulted.update(minority)

            def heal(minority=tuple(minority)):
                self.mesh.heal_all()
                self._faulted = frozenset(self._faulted - set(minority))
            deferred.append((r + params.get("hold", 2), heal,
                             f"heal {sorted(minority)}|{len(majority)}"))
        elif kind == "delay_link":
            src, dst = params["src"], params["dst"]
            self.mesh.set_link(src, dst, delay=params.get("delay", 0.2))

            def undelay(src=src, dst=dst):
                self.mesh.set_link(src, dst, delay=0.0)
            deferred.append((r + params.get("hold", 1), undelay,
                             f"undelay {src}->{dst}"))
        elif kind == "reset_link":
            self.mesh.link(params["src"], params["dst"]).reset_streams()
        else:
            raise FleetError(f"unknown fault kind {kind!r}")
        self._faulted = frozenset(faulted)
        return deferred

    # -- teardown ------------------------------------------------------------

    def stop_all(self) -> dict:
        """SIGTERM every live daemon, reap with a hard budget, return
        {name: exit code}.  Codes: 0 clean, 1 drain overran, 3 leaked
        service threads, negative = died by signal."""
        codes = {}
        for nm, nd in sorted(self.nodes.items()):
            if nd.alive():
                nd.sigterm()
        for nm, nd in sorted(self.nodes.items()):
            if nd.proc is not None:
                codes[nm] = nd.reap(timeout=self.grace + REAP_TIMEOUT)
        return codes

    def teardown(self) -> None:
        """Last-resort cleanup (context-manager exit): kill anything
        still alive, reap bounded, stop every proxy."""
        for nd in self.nodes.values():
            if nd.alive():
                nd.proc.kill()
        for nd in self.nodes.values():
            if nd.proc is not None:
                try:
                    nd.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        self.mesh.stop()


# -- invariants ---------------------------------------------------------------

class FleetInvariants:
    """Post-hoc checks over a soaked fleet; every method raises
    FleetError with enough context to debug from a CI log."""

    def __init__(self, fleet: Fleet):
        self.fleet = fleet

    def assert_no_fork(self, up_to: int, nodes=None) -> int:
        """Byte-identical signatures across nodes at every round.  A
        node missing a round (pruned/still syncing) is skipped, a
        DIFFERENT byte string is a fork.  Returns rounds compared."""
        names = list(nodes or self.fleet.nodes)
        compared = 0
        for r in range(1, up_to + 1):
            sigs = {}
            for nm in names:
                b = self.fleet.beacon(nm, r)
                if b is not None and b.round == r:
                    sigs[nm] = bytes(b.signature)
            if len(set(sigs.values())) > 1:
                raise FleetError(
                    f"CHAIN FORK at round {r}: "
                    f"{ {nm: s.hex()[:16] for nm, s in sigs.items()} }")
            if len(sigs) >= 2:
                compared += 1
        return compared

    def assert_caught_up(self, name: str, timeout: float) -> None:
        """Recovery: `name` serves a head within 1 round of the fleet
        maximum before `timeout` real seconds pass."""
        deadline = time.monotonic() + timeout
        gap, mine, best = None, None, None
        while time.monotonic() < deadline:
            heads = {nm: self.fleet.head(nm) for nm in self.fleet.nodes}
            rounds = {nm: h.round for nm, h in heads.items()
                      if h is not None}
            if name in rounds and rounds:
                mine, best = rounds[name], max(rounds.values())
                gap = best - mine
                if gap <= 1:
                    return
            time.sleep(0.3)
        raise FleetError(
            f"recovery: {name} stuck {gap} rounds behind "
            f"(head {mine} vs fleet max {best}) after {timeout}s")

    def assert_restart_counts(self) -> None:
        """Every node's persisted restarts.json agrees with the
        supervisor's own spawn bookkeeping."""
        for nm, nd in self.fleet.nodes.items():
            path = os.path.join(nd.folder, "restarts.json")
            try:
                with open(path) as f:
                    starts = int(json.load(f).get("starts", 0))
            except (OSError, ValueError):
                raise FleetError(f"{nm}: unreadable {path}")
            if starts != nd.starts:
                raise FleetError(
                    f"{nm}: restarts.json says {starts} starts, "
                    f"supervisor spawned {nd.starts}")

    def assert_clean_exit(self, codes: dict) -> None:
        bad = {nm: rc for nm, rc in codes.items() if rc != 0}
        if bad:
            raise FleetError(
                f"unclean exits {bad} (1=drain overran, 3=leaked "
                "service threads, negative=killed by signal)")


# -- canned scenario ----------------------------------------------------------

def smoke_soak(base_dir: str, n: int = 5, rounds: int = 5, seed: int = 7,
               period: int = 3, mtls: bool = False, log=print) -> dict:
    """The acceptance scenario, shared by tests/test_fleet.py,
    tools/fleet.py and chaos_smoke --fleet: live-gRPC DKG across `n`
    processes, `rounds` Handel rounds, one SIGKILL + restart + catch-up,
    one seeded minority partition + heal, then a SIGTERM-all teardown.
    With `mtls` every plane (DKG, Handel, observation, restarts through
    the proxies) runs over per-node certs with required client auth.
    Returns a result dict for logs/CI artifacts."""
    rng = random.Random(seed)
    with Fleet(n, base_dir, period=period, seed=seed, mtls=mtls,
               log=log) as fleet:
        fleet.start()
        group = fleet.run_dkg()
        inv = FleetInvariants(fleet)
        fleet.wait_round(2, fleet.liveness_budget(2))

        # crash one member mid-soak; the survivors must keep advancing
        victim = f"n{rng.randrange(n)}"
        log(f"SIGKILL {victim}")
        fleet.nodes[victim].kill()
        fleet.nodes[victim].reap()
        others = [nm for nm in fleet.nodes if nm != victim]
        fleet.wait_round(3, fleet.liveness_budget(2), nodes=others)
        fleet.nodes[victim].restart()
        fleet._write_dial_map(victim)
        inv.assert_caught_up(victim, fleet.liveness_budget(6))

        # seeded minority partition through the proxies, then heal; the
        # majority side must never stall
        minority = sorted(rng.sample(sorted(fleet.nodes), (n - 1) // 2))
        majority = [nm for nm in fleet.nodes if nm not in minority]
        log(f"partition {minority} | {majority}")
        fleet.mesh.partition(minority, majority)
        head0 = max((getattr(fleet.head(nm), "round", 0) or 0)
                    for nm in majority)
        fleet.wait_round(head0 + 1, fleet.liveness_budget(2),
                         nodes=majority)
        fleet.mesh.heal_all()
        for nm in minority:
            inv.assert_caught_up(nm, fleet.liveness_budget(6))

        fleet.wait_round(rounds, fleet.liveness_budget(rounds))
        compared = inv.assert_no_fork(rounds)
        inv.assert_restart_counts()
        codes = fleet.stop_all()
        inv.assert_clean_exit(codes)
        return {
            "n": n, "rounds": rounds, "seed": seed, "mtls": mtls,
            "group_hash": group.hash().hex(),
            "rounds_compared": compared,
            "victim": victim, "minority": minority,
            "exit_codes": codes,
            "proxy_stats": fleet.mesh.stats(),
        }
