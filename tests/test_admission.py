"""Serving-plane admission control (net/admission.py + its wiring):
priority-class reservation, per-peer fair share, hysteresis, wire shapes
(HTTP 429 / gRPC RESOURCE_EXHAUSTED), the degradation ladder, and the
bounded REST edge."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from drand_tpu.beacon.clock import FakeClock, RealClock
from drand_tpu.net.admission import (CLASS_CRITICAL, CLASS_NORMAL,
                                     CLASS_SHEDDABLE, LEVEL_NOMINAL,
                                     LEVEL_PAUSE_BACKGROUND,
                                     LEVEL_SHED_NORMAL, LEVEL_SHED_PUBLIC,
                                     REASON_PEER_CAP, AdmissionController,
                                     Shed, classify_method)

from harness import assert_no_leaked_rest_threads, rest_threads


def _controller(**kw):
    kw.setdefault("clock", FakeClock(1_000.0))
    kw.setdefault("capacity", 6)
    kw.setdefault("critical_reserve", 2)
    kw.setdefault("shed_wait", 0.5)
    kw.setdefault("recover_wait", 0.05)
    kw.setdefault("dwell", 2.0)
    kw.setdefault("normal_wait", 1.0)
    return AdmissionController(**kw)


# -- priority classes ---------------------------------------------------------


def test_critical_reserved_while_sheddable_sheds():
    """The reserve: with every non-critical token taken, sheddable sheds
    immediately and critical keeps being admitted — partials must never
    wait behind public reads."""
    ctrl = _controller()                    # 6 total, 4 non-critical
    held = [ctrl.admit(CLASS_SHEDDABLE) for _ in range(4)]
    with pytest.raises(Shed) as e:
        ctrl.admit(CLASS_SHEDDABLE)
    assert e.value.cls == CLASS_SHEDDABLE
    assert e.value.retry_after > 0
    crit = [ctrl.admit(CLASS_CRITICAL) for _ in range(8)]
    assert ctrl.wait_p99(CLASS_CRITICAL) == 0.0
    for t in crit + held:
        t.release()


def test_normal_times_out_and_the_wait_is_recorded():
    """A normal request that cannot get a token within `normal_wait`
    sheds, and its timed-out wait lands in the p99 window — the overload
    signal the ladder climbs on."""
    clock = FakeClock(1_000.0)
    ctrl = _controller(clock=clock)
    held = [ctrl.admit(CLASS_SHEDDABLE) for _ in range(4)]
    out = {}

    def attempt():
        try:
            out["t"] = ctrl.admit(CLASS_NORMAL, peer="peer1")
        except Shed as s:
            out["s"] = s

    th = threading.Thread(target=attempt, daemon=True)
    th.start()
    deadline = time.monotonic() + 5
    while th.is_alive() and time.monotonic() < deadline:
        clock.advance(0.25)
        time.sleep(0.02)
    th.join(2)
    assert "s" in out, "normal admit should have timed out"
    assert ctrl.wait_p99(CLASS_NORMAL) >= ctrl.normal_wait
    for t in held:
        t.release()


def test_per_peer_fair_share_stream_cap():
    ctrl = _controller(max_streams_per_peer=2)
    a1 = ctrl.admit(CLASS_NORMAL, peer="hog", stream=True)
    a2 = ctrl.admit(CLASS_NORMAL, peer="hog", stream=True)
    with pytest.raises(Shed) as e:
        ctrl.admit(CLASS_NORMAL, peer="hog", stream=True)
    assert e.value.reason == REASON_PEER_CAP
    # a DIFFERENT peer is not punished for the hog's appetite
    b1 = ctrl.admit(CLASS_NORMAL, peer="polite", stream=True)
    for t in (a1, a2, b1):
        t.release()
    # the cap is per-CONCURRENT-streams: after release the peer is fine
    again = ctrl.admit(CLASS_NORMAL, peer="hog", stream=True)
    again.release()


def test_pacing_bucket_math():
    """Past the burst allowance, each streamed item costs 1/rate seconds
    of bucket time at the fair-share rate; uncontended streams are never
    paced (and their history is forgiven)."""
    clock = FakeClock(1_000.0)
    ctrl = _controller(clock=clock, pace_rate=100.0, pace_burst=10)
    ctrl.WAIT_REAL_CAP = 0.05       # the fake deadline never arrives here
    solo = ctrl.admit(CLASS_NORMAL, peer="a", stream=True)
    assert solo.pace(1_000) == 0.0              # uncontended: full pipe
    other = ctrl.admit(CLASS_NORMAL, peer="b", stream=True)
    t0 = clock.monotonic()
    for _ in range(2):
        solo.pace(10)                           # 20 items, burst is 10
    # 2 streams -> 50 items/s fair share; 10 past-burst items owe 0.2s
    assert solo._next_ok - t0 >= 10 / 50 - 1e-9
    solo.release()
    other.release()


# -- hysteresis ---------------------------------------------------------------


def _drive_timeout(ctrl, clock, peer="p"):
    """One normal-class admission timeout with the clock stepped from the
    main thread (deterministic fake-time wait)."""
    out = {}

    def attempt():
        try:
            out["t"] = ctrl.admit(CLASS_NORMAL, peer=peer)
        except Shed as s:
            out["s"] = s

    th = threading.Thread(target=attempt, daemon=True)
    th.start()
    deadline = time.monotonic() + 5
    while th.is_alive() and time.monotonic() < deadline:
        clock.advance(0.25)
        time.sleep(0.015)
    th.join(2)
    if "t" in out:
        out["t"].release()
    return out


def test_ladder_hysteresis_no_flapping_on_fakeclock():
    """The ladder climbs one rung per dwell under pressure, never
    oscillates while the p99 sits between the recover and shed
    thresholds, and steps back down one rung per dwell once the window
    drains — strictly up, then strictly down, no flapping."""
    clock = FakeClock(1_000.0)
    ctrl = _controller(clock=clock, dwell=2.0)
    held = [ctrl.admit(CLASS_SHEDDABLE) for _ in range(4)]

    # sustained pressure: timed-out normal waits while the pool is full
    levels = [ctrl.level()]
    for _ in range(8):
        _drive_timeout(ctrl, clock)
        clock.advance(ctrl.dwell)
        levels.append(ctrl.level())
    assert max(levels) == LEVEL_SHED_NORMAL
    ups = [lv for lv in levels if lv != 0]
    assert ups == sorted(ups), f"ladder flapped on the way up: {levels}"

    # pressure stops: tokens free, the wait window drains, and the
    # ladder walks down one rung per dwell without ever bouncing back
    for t in held:
        t.release()
    clock.advance(ctrl._window + 1)
    down = []
    for _ in range(8):
        clock.advance(ctrl.dwell)
        down.append(ctrl.level())
    assert down[-1] == LEVEL_NOMINAL
    assert down == sorted(down, reverse=True), f"flapped down: {down}"
    # transition log shows single-step moves only
    steps = [lvl for _, lvl in ctrl.snapshot()["transitions"]]
    assert all(abs(b - a) == 1 for a, b in zip(steps, steps[1:]))


def test_ladder_orders_background_pause_before_normal_shed():
    """Level 2 (pause background) is strictly below level 3 (shed
    normal): the hook fires before any normal-class level shed, and
    resumes on the way down."""
    clock = FakeClock(1_000.0)
    events = []
    ctrl = _controller(clock=clock, dwell=2.0,
                       background_hook=lambda p: events.append(
                           (clock.monotonic(), p)))
    held = [ctrl.admit(CLASS_SHEDDABLE) for _ in range(4)]
    first_normal_level_shed = None
    for _ in range(6):
        _drive_timeout(ctrl, clock)
        clock.advance(ctrl.dwell)
        lvl = ctrl.level()
        if lvl >= LEVEL_SHED_NORMAL and first_normal_level_shed is None:
            with pytest.raises(Shed):
                ctrl.admit(CLASS_NORMAL, peer="x")
            first_normal_level_shed = clock.monotonic()
    assert first_normal_level_shed is not None
    assert events and events[0][1] is True
    assert events[0][0] < first_normal_level_shed
    assert ctrl.background_paused()
    for t in held:
        t.release()
    clock.advance(ctrl._window + 1)
    for _ in range(6):
        clock.advance(ctrl.dwell)
        ctrl.level()
    assert events[-1][1] is False and not ctrl.background_paused()


def test_background_pause_reaches_verify_service():
    """Config glue: the ladder's hook pauses the verify service's
    BACKGROUND lane — queued work waits (never fails) and flushes on
    resume while LIVE work keeps flowing."""
    import numpy as np

    from drand_tpu.core.config import Config
    from drand_tpu.crypto.schemes import scheme_from_name

    class _Echo:            # instant fake backend
        kind = "host"

        def verify_batch(self, rounds, sigs, prevs=None):
            return np.ones(len(rounds), dtype=bool)

    cfg = Config(clock=RealClock(), verify_window=0.0)
    svc = cfg.verify_service()
    try:
        scheme = scheme_from_name("pedersen-bls-chained")
        # distinct chains: a queued background request of the SAME chain
        # would legitimately ride the live dispatch for free
        h_bg = svc.handle(scheme, b"\x01" * 96, backend=_Echo())
        h_live = svc.handle(scheme, b"\x02" * 96, backend=_Echo())
        cfg._pause_background(True)
        assert svc.background_paused()
        bg = h_bg.submit([1], [b"x"], lane="background", flush_now=True)
        live = h_live.submit([2], [b"y"], lane="live", flush_now=True)
        assert live.result(5).all()         # live unaffected
        time.sleep(0.2)
        assert not bg.done()                # background parked, not failed
        cfg._pause_background(False)
        assert bg.result(5).all()           # resumes flush-ready
    finally:
        cfg.stop_verify_service()


# -- wire shapes --------------------------------------------------------------


def test_rest_429_shape_and_recovery():
    """The REST edge sheds BEFORE parsing with a complete 429: status,
    Retry-After, JSON body, connection close — and serves again the
    moment a token frees."""
    from types import SimpleNamespace

    from drand_tpu.http_server import RestServer
    from drand_tpu.log import Logger

    ctrl = _controller(capacity=3, critical_reserve=2)  # 1 sheddable token
    daemon = SimpleNamespace(processes={}, chain_hashes={},
                             log=Logger("t"))
    server = RestServer(daemon, "127.0.0.1:0", admission=ctrl,
                        clock=RealClock(), workers=2)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with urllib.request.urlopen(base + "/chains", timeout=5) as r:
            assert r.status == 200
        # the serving worker releases its token asynchronously after the
        # response body: retry-grab the one sheddable token briefly
        deadline = time.monotonic() + 3
        while True:
            try:
                held = ctrl.admit(CLASS_SHEDDABLE)
                break
            except Shed:
                assert time.monotonic() < deadline
                time.sleep(0.02)
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/chains", timeout=5)
        assert e.value.code == 429
        assert float(e.value.headers["Retry-After"]) > 0
        assert e.value.headers["Connection"] == "close"
        assert json.loads(e.value.read())["error"] == "overloaded"
        held.release()
        with urllib.request.urlopen(base + "/chains", timeout=5) as r:
            assert r.status == 200
    finally:
        server.stop()


@pytest.fixture()
def admitted_loopback():
    import grpc  # noqa: F401

    from drand_tpu.net import Listener, Peer, ProtocolClient, services
    from drand_tpu.protos import drand_pb2 as pb

    release = threading.Event()

    class _Protocol:
        def partial_beacon(self, req, ctx):
            return pb.Empty()

        def sync_chain(self, req, ctx):
            yield pb.BeaconPacket(round=req.from_round,
                                  signature=b"\x01" * 4)
            release.wait(10)    # hold the stream open for the cap test

        def __getattr__(self, name):
            def f(req, ctx):
                return pb.Empty()
            return f

    class _Public:
        def public_rand(self, req, ctx):
            return pb.PublicRandResponse(round=req.round or 7,
                                         signature=b"sig")

        def __getattr__(self, name):
            def f(req, ctx):
                return pb.Empty()
            return f

    ctrl = _controller(capacity=8, critical_reserve=2,
                       max_streams_per_peer=2)
    lis = Listener("127.0.0.1:0",
                   [(services.PROTOCOL, _Protocol()),
                    (services.PUBLIC, _Public())], admission=ctrl)
    lis.start()
    client = ProtocolClient()
    yield client, Peer(f"127.0.0.1:{lis.port}"), ctrl, release, pb
    release.set()
    client.close()
    lis.stop()


def test_grpc_resource_exhausted_shape(admitted_loopback):
    import grpc

    client, peer, ctrl, release, pb = admitted_loopback
    assert client.public_rand(peer).round == 7
    held = [ctrl.admit(CLASS_SHEDDABLE) for _ in range(6)]  # pool full
    with pytest.raises(grpc.RpcError) as e:
        client.public_rand(peer)
    assert e.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
    md = dict(e.value.trailing_metadata() or ())
    assert float(md["retry-after"]) > 0
    assert "sheddable" in e.value.details()
    # critical (partials) rides the reserve straight through
    client.partial_beacon(peer, pb.PartialBeaconPacket(
        round=1, partial_sig=b"x"))
    for t in held:
        t.release()


def test_sync_chain_per_peer_cap_over_grpc(admitted_loopback):
    import grpc

    client, peer, ctrl, release, pb = admitted_loopback
    s1 = client.sync_chain(peer, 1)
    s2 = client.sync_chain(peer, 1)
    assert next(iter(s1)).round == 1        # both streams admitted
    assert next(iter(s2)).round == 1
    s3 = client.sync_chain(peer, 1)
    with pytest.raises(grpc.RpcError) as e:
        next(iter(s3))
    assert e.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
    release.set()                           # drain + release the streams
    list(s1), list(s2)


def test_peer_identity_strips_ephemeral_port():
    """The fair-share key is the remote HOST: a hog must not evade the
    stream cap by opening one channel (one ephemeral port) per stream."""
    from drand_tpu.net.admission import peer_identity

    assert peer_identity("ipv4:10.0.0.1:52644") == "ipv4:10.0.0.1"
    assert peer_identity("ipv4:10.0.0.1:9") == \
        peer_identity("ipv4:10.0.0.1:52645")
    assert peer_identity("ipv6:[::1]:52644") == "ipv6:[::1]"
    assert peer_identity("ipv6:[::1]") == "ipv6:[::1]"
    assert peer_identity("hog") == "hog"            # scenario names
    assert peer_identity("127.0.0.1") == "127.0.0.1"  # REST client addr


def test_grpc_worker_pool_sized_past_the_token_pool(admitted_loopback):
    """Tokens must be the binding constraint: a Listener built with an
    admission controller sizes its executor past `capacity` so the
    interceptor always runs before any queueing."""
    from concurrent import futures as _f

    from drand_tpu.net import Listener, services
    from drand_tpu.protos import drand_pb2 as pb  # noqa: F401

    _, _, ctrl, _, _ = admitted_loopback
    captured = {}
    orig = _f.ThreadPoolExecutor

    class Spy(orig):
        def __init__(self, max_workers=None, **kw):
            captured["max_workers"] = max_workers
            super().__init__(max_workers=max_workers, **kw)

    _f.ThreadPoolExecutor = Spy
    try:
        lis = Listener("127.0.0.1:0", [], admission=ctrl)
    finally:
        _f.ThreadPoolExecutor = orig
    try:
        assert captured["max_workers"] >= ctrl.capacity + 8
    finally:
        lis.stop()


def test_classify_method_map():
    assert classify_method("/drand.Protocol/PartialBeacon") == CLASS_CRITICAL
    assert classify_method("/drand.Protocol/BroadcastDKG") == CLASS_CRITICAL
    assert classify_method("/drand.Protocol/SyncChain") == CLASS_NORMAL
    assert classify_method("/drand.Public/PublicRand") == CLASS_SHEDDABLE
    assert classify_method("/drand.Public/ChainInfo") == CLASS_SHEDDABLE
    assert classify_method("/drand.Control/Shutdown") is None


# -- the bounded REST edge ----------------------------------------------------


def test_rest_worker_pool_is_bounded_and_reaped():
    """Satellite: request traffic must never grow the thread set (the
    old ThreadingHTTPServer spawned one non-daemon thread per request),
    and stop() reaps acceptor + workers (harness leak check)."""
    from types import SimpleNamespace

    from drand_tpu.http_server import RestServer
    from drand_tpu.log import Logger

    before = rest_threads()
    daemon = SimpleNamespace(processes={}, chain_hashes={},
                             log=Logger("t"))
    server = RestServer(daemon, "127.0.0.1:0", clock=RealClock(),
                        workers=4)
    server.start()
    base = f"http://127.0.0.1:{server.port}"

    def hit():
        try:
            with urllib.request.urlopen(base + "/chains", timeout=5) as r:
                r.read()
        except Exception:
            pass

    for _ in range(30):                     # sequential
        hit()
    burst = [threading.Thread(target=hit, daemon=True) for _ in range(12)]
    for t in burst:
        t.start()
    mid = [t for t in rest_threads() if t not in before]
    for t in burst:
        t.join(5)
    # acceptor + exactly `workers` pool threads, regardless of traffic
    assert len(mid) <= 1 + 4, [t.name for t in mid]
    assert all(t.daemon for t in mid)
    server.stop()
    assert_no_leaked_rest_threads(before=before)


# -- the full overload scenario ----------------------------------------------


def test_overload_scenario_acceptance():
    """The ISSUE acceptance: seeded read flood + sync-hog peer during
    live rounds — partials p99 under one round period, well-formed
    sheds, background paused before any normal shed, fair-share-bounded
    hog, hysteretic recovery."""
    from chaos import OverloadScenario

    r = OverloadScenario(seed=42).run()
    assert r.partials_p99 < r.period
    assert r.sheds_well_formed and r.shed_reads > 0
    assert r.peer_cap_sheds > 0
    assert r.paced and r.hog_rounds <= r.hog_bound
    assert r.max_level == LEVEL_SHED_NORMAL
    assert r.ladder_ordered, (r.bg_pause_at, r.first_normal_shed_at)
    assert r.bg_resumed and r.final_level == LEVEL_NOMINAL
    assert r.ok


def test_overload_scenario_deterministic_verdict():
    """Two runs, same seed: the structural verdict is stable (thread
    interleaving may wiggle counts, never the pass/fail shape)."""
    from chaos import OverloadScenario

    a = OverloadScenario(seed=9, flood_seconds=20,
                         recover_seconds=30).run()
    b = OverloadScenario(seed=9, flood_seconds=20,
                         recover_seconds=30).run()
    assert a.ok and b.ok
    assert a.max_level == b.max_level
    assert a.ladder_ordered and b.ladder_ordered
