"""Device-vs-host DKG math parity (crypto/dkg_device.py; ISSUE 13).

Property-style cases: tampered commitments, wrong-index shares, and
reshare constant-term mismatches must be rejected IDENTICALLY by the
batched device pipelines and the host `_share_matches` path.  Shapes
here stay small (the pipelines are shape-polymorphic scans, so the
compiled programs are the same ones the n=1024 committee test in
test_committee.py exercises at scale)."""

import secrets

import pytest

from drand_tpu.crypto import dkg as D
from drand_tpu.crypto import dkg_device as DD
from drand_tpu.crypto import tbls
from drand_tpu.crypto.host.params import R
from drand_tpu.crypto.schemes import scheme_from_name


@pytest.fixture(scope="module")
def scheme():
    return scheme_from_name("pedersen-bls-chained")


@pytest.fixture()
def force_device(monkeypatch):
    monkeypatch.setattr(DD, "MIN_N", 2)


def _dealers(g, m, t, rng):
    polys = [tbls.PriPoly([rng.randrange(R) for _ in range(t)])
             for _ in range(m)]
    return polys, [p.commit(g) for p in polys]


# ---------------------------------------------------------------------------
# routing predicate
# ---------------------------------------------------------------------------

def test_use_device_threshold(monkeypatch):
    monkeypatch.setattr(DD, "MIN_N", 64)
    assert not DD.use_device(63)
    assert DD.use_device(64) == DD.available()
    monkeypatch.setattr(DD, "MIN_N", 0)
    assert not DD.use_device(10 ** 6)       # 0 disables outright
    assert DD.use_device(8, min_n=4) == DD.available()


def test_small_sessions_stay_on_host(monkeypatch, scheme):
    """Below the lane threshold the dkg module must never touch the
    device module's batch entry points."""
    monkeypatch.setattr(DD, "MIN_N", 64)
    monkeypatch.setattr(DD, "verify_shares",
                        lambda *a, **k: pytest.fail("device path taken"))
    g = scheme.key_group
    rng = __import__("random").Random(5)
    polys, pubs = _dealers(g, 3, 3, rng)
    gen = D.DistKeyGenerator.__new__(D.DistKeyGenerator)
    gen.scheme = scheme
    gen.holder_index = 1
    gen._my_shares = {}
    cands = [(type("B", (), {"dealer_index": d})(), pubs[d],
              polys[d].eval(1).value) for d in range(3)]
    gen._adopt_matching_shares(cands)
    assert set(gen._my_shares) == {0, 1, 2}


# ---------------------------------------------------------------------------
# share verification parity
# ---------------------------------------------------------------------------

def test_verify_shares_parity_under_tampering(scheme):
    """Wrong-index shares, random-garbage shares, tampered commitments:
    device and host accept/reject sets are bit-identical."""
    g = scheme.key_group
    rng = __import__("random").Random(7)
    m, t, holder = 8, 4, 3
    polys, pubs = _dealers(g, m, t, rng)
    shares = [p.eval(holder).value for p in polys]
    shares[1] = polys[1].eval(holder + 1).value          # wrong index
    shares[2] = rng.randrange(R)                         # garbage
    pubs[4].commits[2] = g.curve.mul(g.curve.gen, rng.randrange(R))
    pubs[6].commits[0] = g.curve.mul(g.curve.gen, rng.randrange(R))
    commits_list = [list(p.commits) for p in pubs]
    host = [g.curve.mul(g.curve.gen, s) == pubs[d].eval(holder)
            for d, s in enumerate(shares)]
    before = DD.dispatch_count()
    dev = DD.verify_shares(g, commits_list, holder, shares)
    assert DD.dispatch_count() - before == 1
    assert dev == host
    assert dev[0] and dev[3]                # honest dealers still accepted
    assert not (dev[1] or dev[2])


def test_verify_shares_zero_and_infinity_edges(scheme):
    """share = 0 (infinity LHS) and an infinity commitment both follow
    the host verdict exactly (the complete add formulas absorb them)."""
    g = scheme.key_group
    rng = __import__("random").Random(11)
    m, t, holder = 4, 3, 0
    polys, pubs = _dealers(g, m, t, rng)
    shares = [p.eval(holder).value for p in polys]
    shares[1] = 0                                        # forged zero share
    pubs[2].commits[1] = None                            # infinity commit
    host = [g.curve.mul(g.curve.gen, s) == pubs[d].eval(holder)
            for d, s in enumerate(shares)]
    dev = DD.verify_shares(g, [list(p.commits) for p in pubs],
                           holder, shares)
    assert dev == host


def test_eval_all_matches_host_pubpoly(scheme):
    g = scheme.key_group
    rng = __import__("random").Random(13)
    _, pubs = _dealers(g, 1, 5, rng)
    pub = pubs[0]
    idxs = list(range(9))
    dev = DD.eval_all(g, list(pub.commits), idxs)
    fresh = tbls.PubPoly(g, list(pub.commits))      # memo-free oracle
    assert dev == [fresh.eval(i) for i in idxs]


def test_constant_terms_match_parity(scheme):
    g = scheme.key_group
    rng = __import__("random").Random(17)
    _, (old,) = _dealers(g, 1, 4, rng)
    m = 6
    claimed = [old.eval(d) for d in range(m)]
    claimed[2] = g.curve.mul(g.curve.gen, 424242)        # key-change attempt
    claimed[5] = None
    got = DD.constant_terms_match(g, list(old.commits), range(m), claimed)
    assert got == [True, True, False, True, True, False]


def test_combine_commits_parity(scheme):
    g = scheme.key_group
    rng = __import__("random").Random(19)
    m, t = 5, 3
    _, pubs = _dealers(g, m, t, rng)
    matrix = [list(p.commits) for p in pubs]
    lams = [rng.randrange(R) for _ in range(m)]
    dev = DD.combine_commits(g, matrix, lams)
    host = []
    for j in range(t):
        acc = None
        for d in range(m):
            acc = g.curve.add(acc, g.curve.mul(matrix[d][j], lams[d]))
        host.append(acc)
    assert dev == host
    # plain-sum flavor (fresh DKG finalize)
    dev2 = DD.combine_commits(g, matrix)
    host2 = []
    for j in range(t):
        acc = None
        for d in range(m):
            acc = g.curve.add(acc, matrix[d][j])
        host2.append(acc)
    assert dev2 == host2


# ---------------------------------------------------------------------------
# the full state machine over the device path
# ---------------------------------------------------------------------------

def _fresh_session(scheme, n, thr, nonce=b"n" * 32):
    g = scheme.key_group
    secs = [secrets.randbelow(1 << 200) for _ in range(n)]
    nodes = [D.DkgNode(i, g.to_bytes(g.curve.mul(g.curve.gen, s)))
             for i, s in enumerate(secs)]
    gens = [D.DistKeyGenerator(D.DkgConfig(
        scheme=scheme, longterm=secs[i], nonce=nonce,
        new_nodes=nodes, threshold=thr)) for i in range(n)]
    return secs, nodes, gens


def test_full_dkg_device_path_matches_host(scheme, force_device):
    """The same deal bundles processed by a device-routed and a
    host-routed node must produce identical shares and commitments."""
    n, thr = 5, 3
    secs, nodes, gens = _fresh_session(scheme, n, thr)
    deals = [x.generate_deals() for x in gens]
    # tamper dealer 3's deal to holder 0: encrypted garbage -> decrypt
    # fails; tamper dealer 4's commitments after signing -> sig reject
    deals[3].deals[0].encrypted = bytes(64)
    deals[4].commits[1] = deals[4].commits[0]
    resps = [x.process_deal_bundles(deals) for x in gens]
    # holder 0 complains about dealer 3 AND dealer 4 (bad bundle sig)
    st0 = {r.dealer_index: r.status for r in resps[0].responses}
    assert st0[3] == D.STATUS_COMPLAINT and st0[4] == D.STATUS_COMPLAINT
    # a host-routed twin (fresh generator, device off) agrees exactly
    import drand_tpu.crypto.dkg_device as dd
    old_min = dd.MIN_N
    dd.MIN_N = 10 ** 9
    try:
        twin = D.DistKeyGenerator(D.DkgConfig(
            scheme=scheme, longterm=secs[0], nonce=b"n" * 32,
            new_nodes=nodes, threshold=thr))
        twin_resp = twin.process_deal_bundles(deals)
    finally:
        dd.MIN_N = old_min
    assert {r.dealer_index: r.status for r in twin_resp.responses} == st0
    assert twin._my_shares == gens[0]._my_shares


def test_duplicate_dealer_bundles_first_wins(scheme):
    """An equivocating dealer sending TWO validly-signed bundles in one
    batch must not get bundle B stored while the share was decrypted
    from bundle A (review finding: the staged restructure briefly lost
    the in-batch dedup).  The first bundle wins, and the stored bundle
    and adopted share stay consistent."""
    n, thr = 4, 3
    secs, nodes, gens = _fresh_session(scheme, n, thr)
    deals = [x.generate_deals() for x in gens]
    evil_twin = D.DistKeyGenerator(D.DkgConfig(
        scheme=scheme, longterm=secs[0], nonce=b"n" * 32,
        new_nodes=nodes, threshold=thr))
    second = evil_twin.generate_deals()     # different polynomial, valid sig
    g1 = gens[1]
    g1.process_deal_bundles(deals + [second])
    stored = g1._deal_bundles[0]
    assert stored.hash(b"n" * 32) == deals[0].hash(b"n" * 32)
    pub = tbls.PubPoly.from_bytes(scheme.key_group,
                                  b"".join(stored.commits))
    gcurve = scheme.key_group.curve
    assert gcurve.mul(gcurve.gen, g1._my_shares[0]) == pub.eval(1), \
        "adopted share inconsistent with the stored bundle's commitments"


def test_full_reshare_device_path_preserves_key(scheme, force_device):
    """Reshare over the device path: constant-term pin enforced, Lagrange
    combine on device, collective key byte-identical."""
    n, thr = 5, 3
    secs, nodes, gens = _fresh_session(scheme, n, thr)
    deals = [x.generate_deals() for x in gens]
    resps = [x.process_deal_bundles(deals) for x in gens]
    outs = [x.process_response_bundles(resps)[0] for x in gens]
    assert all(o is not None for o in outs)
    pk = outs[0].public_key()

    rgens = [D.DistKeyGenerator(D.DkgConfig(
        scheme=scheme, longterm=secs[i], nonce=b"r" * 32,
        new_nodes=nodes, threshold=thr, old_nodes=nodes, old_threshold=thr,
        share=outs[i].share, public_coeffs=list(outs[0].commits)))
        for i in range(n)]
    rdeals = [x.generate_deals() for x in rgens]
    # dealer 2 tries to change the collective key: deal a polynomial whose
    # constant term is NOT its old share — the pin must reject the bundle
    evil = D.DistKeyGenerator(D.DkgConfig(
        scheme=scheme, longterm=secs[2], nonce=b"r" * 32,
        new_nodes=nodes, threshold=thr, old_nodes=nodes, old_threshold=thr,
        share=tbls.PriShare(2, 123456789), \
        public_coeffs=list(outs[0].commits)))
    rdeals[2] = evil.generate_deals()
    rresps = [x.process_deal_bundles(rdeals) for x in rgens]
    assert all(2 not in x._valid_dealers for x in rgens), \
        "constant-term pin missed a key-change attempt"
    routs = [x.process_response_bundles(rresps)[0] for x in rgens]
    assert all(o is not None for o in routs)
    assert {o.public_key() for o in routs} == {pk}, "collective key drifted"


def test_prime_public_shares_one_dispatch(scheme):
    g = scheme.key_group
    rng = __import__("random").Random(23)
    _, (pubp,) = _dealers(g, 1, 4, rng)
    pub = tbls.PubPoly(g, list(pubp.commits))
    before = DD.dispatch_count()
    mapping = DD.prime_public_shares(pub, 6)
    assert DD.dispatch_count() - before == 1
    assert set(mapping) == set(range(6))
    # memo primed: evals are lookups that agree with the device values
    oracle = tbls.PubPoly(g, list(pubp.commits))
    for i in range(6):
        assert pub.eval(i) == oracle.eval(i) == mapping[i]
