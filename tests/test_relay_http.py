"""L8 edge: relays + REST server routes over a mock chain."""

import json
import threading
import urllib.request

import pytest

from drand_tpu.relay import (DirObjectStore, GrpcRelayNode, HttpRelay,
                             ObjectStoreRelay, S3ObjectStore,
                             ValidatingWatch)
from drand_tpu.client import GrpcTransport
from drand_tpu.log import Logger

from test_client import MockChain, MockSource


@pytest.fixture(scope="module")
def chain():
    return MockChain(n=5)


def test_validating_watch_drops_invalid(chain):
    from drand_tpu.chain.beacon import Beacon
    src = MockSource(chain)
    # corrupt round 3 in a copy of the chain
    src.chain = MockChain.__new__(MockChain)
    src.chain.beacons = dict(chain.beacons)
    good = chain.beacons[3]
    src.chain.beacons[3] = Beacon(round=3,
                                  signature=chain.beacons[4].signature,
                                  previous_sig=good.previous_sig)
    src.chain.info = chain.info
    vw = ValidatingWatch(src, Logger())
    rounds = [r.round for r in vw.watch(threading.Event())]
    assert 3 not in rounds
    assert set(rounds) == {1, 2, 4, 5}


def test_object_store_relay(chain, tmp_path):
    store = DirObjectStore(str(tmp_path / "bucket"))
    relay = ObjectStoreRelay(MockSource(chain), store)
    n = relay.sync(1, 5)
    assert n == 5
    prefix = chain.info.hash().hex()
    obj = json.loads((tmp_path / "bucket" / prefix / "public" / "3").read_text())
    assert obj["round"] == 3
    assert obj["randomness"] == chain.beacons[3].randomness().hex()
    # live upload path writes latest too
    relay.upload(relay.client.get(5))
    latest = json.loads(
        (tmp_path / "bucket" / prefix / "public" / "latest").read_text())
    assert latest["round"] == 5


def test_s3_store_gated():
    with pytest.raises(RuntimeError, match="boto3"):
        S3ObjectStore("bucket")


def test_http_relay_routes(chain):
    relay = HttpRelay(MockSource(chain))
    relay.start()
    try:
        base = f"http://127.0.0.1:{relay.port}"
        info = json.loads(urllib.request.urlopen(f"{base}/info").read())
        assert info["hash"] == chain.info.hash().hex()
        obj = json.loads(urllib.request.urlopen(f"{base}/public/2").read())
        assert obj["round"] == 2
        latest = json.loads(
            urllib.request.urlopen(f"{base}/public/latest").read())
        assert latest["round"] == 5
        # chain-hash-prefixed route
        obj = json.loads(urllib.request.urlopen(
            f"{base}/{chain.info.hash().hex()}/public/1").read())
        assert obj["round"] == 1
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/{'ab'*32}/public/1")
    finally:
        relay.stop()


def test_grpc_relay_fanout(chain):
    relay = GrpcRelayNode(MockSource(chain))
    relay.start()
    try:
        client = GrpcTransport(relay.address)
        # relay serves chain info from its source
        assert client.info().hash() == chain.info.hash()
        # cache warms as the pump validates the watch
        deadline = threading.Event()
        got = None
        for _ in range(100):
            try:
                got = client.get(0)
                if got.round >= 5:
                    break
            except Exception:
                pass
            deadline.wait(0.1)
        assert got is not None and got.round == 5
        assert got.randomness == chain.beacons[got.round].randomness()
    finally:
        relay.stop()
