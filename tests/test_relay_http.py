"""L8 edge: relays + REST server routes over a mock chain."""

import json
import threading
import time
import urllib.request

import pytest

from drand_tpu.relay import (DirObjectStore, GrpcRelayNode, HttpRelay,
                             ObjectStoreRelay, S3ObjectStore,
                             ValidatingWatch)
from drand_tpu.client import GrpcTransport
from drand_tpu.log import Logger

from test_client import MockChain, MockSource


@pytest.fixture(scope="module")
def chain():
    return MockChain(n=5)


def test_validating_watch_drops_invalid(chain):
    from drand_tpu.chain.beacon import Beacon
    src = MockSource(chain)
    # corrupt round 3 in a copy of the chain
    src.chain = MockChain.__new__(MockChain)
    src.chain.beacons = dict(chain.beacons)
    good = chain.beacons[3]
    src.chain.beacons[3] = Beacon(round=3,
                                  signature=chain.beacons[4].signature,
                                  previous_sig=good.previous_sig)
    src.chain.info = chain.info
    vw = ValidatingWatch(src, Logger())
    rounds = [r.round for r in vw.watch(threading.Event())]
    assert 3 not in rounds
    assert set(rounds) == {1, 2, 4, 5}


def test_object_store_relay(chain, tmp_path):
    store = DirObjectStore(str(tmp_path / "bucket"))
    relay = ObjectStoreRelay(MockSource(chain), store)
    n = relay.sync(1, 5)
    assert n == 5
    prefix = chain.info.hash().hex()
    obj = json.loads((tmp_path / "bucket" / prefix / "public" / "3").read_text())
    assert obj["round"] == 3
    assert obj["randomness"] == chain.beacons[3].randomness().hex()
    # live upload path writes latest too
    relay.upload(relay.client.get(5))
    latest = json.loads(
        (tmp_path / "bucket" / prefix / "public" / "latest").read_text())
    assert latest["round"] == 5


class _FakeS3(threading.Thread):
    """Minimal S3-compatible endpoint: stores objects in a dict, checks
    that every request carries a well-formed SigV4 Authorization header."""

    def __init__(self):
        super().__init__(daemon=True, name="fake-s3")
        import http.server

        outer_objects = self.objects = {}
        self.bad_auth = []

        class H(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _key(self):
                # path-style: /<bucket>/<key...>
                return self.path.lstrip("/").split("/", 1)[1]

            def _check_auth(h):
                auth = h.headers.get("Authorization", "")
                ok = (auth.startswith("AWS4-HMAC-SHA256 Credential=")
                      and "SignedHeaders=" in auth and "Signature=" in auth
                      and h.headers.get("x-amz-content-sha256"))
                if not ok:
                    self.bad_auth.append(h.path)
                return ok

            def do_PUT(h):
                if not h._check_auth():
                    h.send_error(403)
                    return
                length = int(h.headers.get("Content-Length", 0))
                outer_objects[h._key()] = h.rfile.read(length)
                h.send_response(200)
                h.end_headers()

            def do_GET(h):
                body = outer_objects.get(h._key())
                if body is None:
                    h.send_error(404)
                    return
                h.send_response(200)
                h.send_header("Content-Length", str(len(body)))
                h.end_headers()
                h.wfile.write(body)

            def do_HEAD(h):
                if h._key() in outer_objects:
                    h.send_response(200)
                    h.end_headers()
                else:
                    h.send_error(404)

        import http.server
        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]

    def run(self):
        self.httpd.serve_forever()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_sigv4_canonical_uri_single_encoded():
    """The canonical URI must be the already-encoded URL path verbatim —
    re-quoting double-encodes keys with space/%/non-ASCII and AWS rejects
    the signature.  Pinned against an independent reference computation."""
    import datetime
    import hashlib
    import hmac as hmac_mod

    from drand_tpu.s3 import SigV4Signer

    signer = SigV4Signer("AK", "SK", "r1")
    now = datetime.datetime(2026, 1, 2, 3, 4, 5,
                            tzinfo=datetime.timezone.utc)
    # key "a b.txt" -> once-encoded path /bkt/a%20b.txt (as _url builds it)
    url = "https://s3.test/bkt/a%20b.txt"
    hdrs = signer.sign("PUT", url, {}, b"payload", now=now)
    sig = hdrs["Authorization"].rsplit("Signature=", 1)[1]

    # independent AWS SigV4 reference: canonical URI is the single-encoded
    # path, NOT quote()d again
    payload_hash = hashlib.sha256(b"payload").hexdigest()
    canonical = "\n".join([
        "PUT", "/bkt/a%20b.txt", "",
        "host:s3.test\n"
        f"x-amz-content-sha256:{payload_hash}\n"
        "x-amz-date:20260102T030405Z\n",
        "host;x-amz-content-sha256;x-amz-date", payload_hash])
    scope = "20260102/r1/s3/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", "20260102T030405Z", scope,
        hashlib.sha256(canonical.encode()).hexdigest()])
    k = b"AWS4SK"
    for part in ("20260102", "r1", "s3", "aws4_request"):
        k = hmac_mod.new(k, part.encode(), hashlib.sha256).digest()
    expect = hmac_mod.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    assert sig == expect


def test_s3_relay_backfill_and_latest(chain):
    """The S3 backend end-to-end: SigV4-signed PUT/HEAD/GET against an
    S3-compatible endpoint, backfill skipping existing objects, immutable
    round objects + mutable latest pointer (cmd/relay-s3/main.go:43-199)."""
    srv = _FakeS3()
    srv.start()
    try:
        store = S3ObjectStore("bkt", region="test-1",
                              endpoint=f"http://127.0.0.1:{srv.port}",
                              access_key="AK", secret_key="SK")
        relay = ObjectStoreRelay(MockSource(chain), store)
        prefix = chain.info.hash().hex()
        # pre-seed round 2 to prove backfill skips existing objects
        store.put(f"{prefix}/public/2", b"preseeded", "application/json")
        n = relay.sync(1, 5)
        assert n == 4, "round 2 existed; only 4 uploads expected"
        assert srv.objects[f"{prefix}/public/2"] == b"preseeded"
        obj = json.loads(srv.objects[f"{prefix}/public/3"])
        assert obj["round"] == 3
        assert obj["randomness"] == chain.beacons[3].randomness().hex()
        # backfill must not have written the latest pointer...
        assert f"{prefix}/public/latest" not in srv.objects
        # ...the live upload path does
        relay.upload(relay.client.get(5))
        latest = json.loads(srv.objects[f"{prefix}/public/latest"])
        assert latest["round"] == 5
        assert store.exists(f"{prefix}/public/5")
        assert store.get(f"{prefix}/public/404") is None
        assert not srv.bad_auth, f"unsigned requests: {srv.bad_auth}"
    finally:
        srv.stop()


def test_http_relay_routes(chain):
    relay = HttpRelay(MockSource(chain))
    relay.start()
    try:
        base = f"http://127.0.0.1:{relay.port}"
        info = json.loads(urllib.request.urlopen(f"{base}/info").read())
        assert info["hash"] == chain.info.hash().hex()
        obj = json.loads(urllib.request.urlopen(f"{base}/public/2").read())
        assert obj["round"] == 2
        latest = json.loads(
            urllib.request.urlopen(f"{base}/public/latest").read())
        assert latest["round"] == 5
        # chain-hash-prefixed route
        obj = json.loads(urllib.request.urlopen(
            f"{base}/{chain.info.hash().hex()}/public/1").read())
        assert obj["round"] == 1
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/{'ab'*32}/public/1")
    finally:
        relay.stop()


def test_grpc_relay_fanout(chain):
    relay = GrpcRelayNode(MockSource(chain))
    relay.start()
    try:
        client = GrpcTransport(relay.address)
        # relay serves chain info from its source
        assert client.info().hash() == chain.info.hash()
        # cache warms as the pump validates the watch
        deadline = threading.Event()
        got = None
        for _ in range(100):
            try:
                got = client.get(0)
                if got.round >= 5:
                    break
            except Exception:
                pass
            deadline.wait(0.1)
        assert got is not None and got.round == 5
        assert got.randomness == chain.beacons[got.round].randomness()
    finally:
        relay.stop()


def test_gossip_mesh_survives_peer_loss(chain):
    """N=5 mesh (lp2p/relaynode.go:34-101 capability): kill the origin's
    direct peer BEFORE any round flows; epidemic forwarding still delivers
    every round to every surviving node, each exactly once (dedup)."""
    from drand_tpu.relay import GossipRelayNode

    nodes = [GossipRelayNode(client=MockSource(chain) if i == 0 else None,
                             info=chain.info, fanout=3)
             for i in range(5)]
    edges = [(0, 1), (0, 2), (1, 3), (2, 3), (3, 4), (2, 4)]
    for a, b in edges:
        nodes[a].add_peer(nodes[b].address)
        nodes[b].add_peer(nodes[a].address)
    try:
        for n in nodes[1:]:
            n.start()
        nodes[1].stop()               # the origin's direct peer dies first
        nodes[0].start()              # now rounds start flowing

        live = [nodes[i] for i in (2, 3, 4)]
        deadline = time.time() + 60
        want = set(chain.beacons)
        while time.time() < deadline:
            if all(want <= set(n._cache) for n in live):
                break
            time.sleep(0.1)
        for i, n in zip((2, 3, 4), live):
            assert want <= set(n._cache), f"node {i} missing rounds"
            assert n.stats["delivered"] == len(want), (i, n.stats)
            assert n.stats["invalid"] == 0
        # the cycle 2-3-4 guarantees duplicate arrivals -> dedup exercised
        assert sum(n.stats["dup"] for n in live) > 0
        # consumers read any mesh node through the ordinary Public service
        client = GrpcTransport(nodes[4].address)
        got = client.get(3)
        assert got.randomness == chain.beacons[3].randomness()
    finally:
        for i, n in enumerate(nodes):
            if i != 1:
                n.stop()


def test_gossip_rejects_invalid_and_foreign(chain):
    """Validate-before-forward (lp2p/client/validator.go:18-68): garbage
    signatures and foreign-chain packets never enter the mesh."""
    from drand_tpu.protos import drand_pb2 as pb
    from drand_tpu.relay import GossipRelayNode

    node = GossipRelayNode(info=chain.info)
    good = chain.beacons[1]
    bad = pb.GossipBeaconPacket(
        chain_hash=chain.info.hash(), round=1,
        signature=b"\x01" * len(good.signature),
        previous_signature=good.previous_sig or b"", sender="x")
    node.on_gossip(bad)
    assert node.stats["invalid"] == 1 and not node._cache
    with pytest.raises(ValueError):
        node.on_gossip(pb.GossipBeaconPacket(
            chain_hash=b"\x00" * 32, round=1, signature=good.signature,
            sender="x"))
    ok = pb.GossipBeaconPacket(
        chain_hash=chain.info.hash(), round=1, signature=good.signature,
        previous_signature=good.previous_sig or b"", sender="x")
    node.on_gossip(ok)
    assert node.stats["delivered"] == 1 and 1 in node._cache
    node.on_gossip(ok)
    assert node.stats["dup"] == 1
    node.stop()
